"""Vision transforms/datasets/models (reference test/legacy_test
vision tests; numeric checks vs numpy references)."""
import gzip
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import datasets, transforms as T
from paddle_tpu.vision.models import (LeNet, MobileNetV2, mobilenet_v2,
                                      vgg11)

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def test_transforms_pipeline():
    img = np.random.RandomState(0).randint(0, 256, (40, 60, 3),
                                           dtype=np.uint8)
    tr = T.Compose([T.Resize(32), T.CenterCrop(32), T.ToTensor(),
                    T.Normalize(mean=[0.5, 0.5, 0.5], std=[0.5, 0.5, 0.5])])
    out = tr(img)
    assert out.shape == [3, 32, 32]
    v = np.asarray(out._value)
    assert v.min() >= -1.001 and v.max() <= 1.001


def test_resize_semantics():
    img = np.zeros((10, 20, 3), np.uint8)
    assert T.resize(img, 5).shape == (5, 10, 3)       # short side
    assert T.resize(img, (7, 9)).shape == (7, 9, 3)   # explicit
    assert T.resize(img, (7, 9), "nearest").shape == (7, 9, 3)


def test_random_transforms_shapes():
    img = np.random.RandomState(1).randint(0, 256, (36, 36, 3),
                                           dtype=np.uint8)
    assert T.RandomCrop(32)(img).shape == (32, 32, 3)
    assert T.RandomHorizontalFlip(1.0)(img).shape == (36, 36, 3)
    np.testing.assert_array_equal(T.RandomHorizontalFlip(1.0)(img),
                                  img[:, ::-1])
    assert T.Pad(2)(img).shape == (40, 40, 3)


def test_mnist_idx_parser(tmp_path):
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (5, 28, 28), dtype=np.uint8)
    lbls = rng.randint(0, 10, (5,)).astype(np.uint8)
    ip = tmp_path / "imgs.gz"
    lp = tmp_path / "lbls.gz"
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 5, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, 5))
        f.write(lbls.tobytes())
    ds = datasets.MNIST(image_path=str(ip), label_path=str(lp))
    assert len(ds) == 5
    img, lbl = ds[2]
    np.testing.assert_array_equal(img, imgs[2])
    assert lbl == lbls[2]


def test_cifar_pickle_parser(tmp_path):
    rng = np.random.RandomState(1)
    data = rng.randint(0, 256, (4, 3 * 32 * 32), dtype=np.uint8)
    batch = {b"data": data, b"labels": [0, 1, 2, 3]}
    p = tmp_path / "test_batch"
    with open(p, "wb") as f:
        pickle.dump(batch, f)
    ds = datasets.Cifar10(data_file=str(p), mode="test")
    assert len(ds) == 4
    img, lbl = ds[1]
    assert img.shape == (32, 32, 3) and lbl == 1


def test_fakedata_with_loader():
    ds = datasets.FakeData(num_samples=16, image_shape=(1, 28, 28),
                           num_classes=10, transform=T.Compose(
                               [T.ToTensor()]))
    from paddle_tpu.io import DataLoader

    batch = next(iter(DataLoader(ds, batch_size=4)))
    assert batch[0].shape == [4, 1, 28, 28]
    assert batch[1].shape == [4]


def test_lenet_trains():
    paddle.seed(0)
    model = LeNet()
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 1, 28, 28)
                         .astype("float32"))
    out = model(x)
    assert out.shape == [2, 10]
    loss = paddle.mean(out ** 2)
    loss.backward()
    assert model.features[0].weight.grad is not None


def test_vgg_and_mobilenet_forward():
    paddle.seed(1)
    x = paddle.to_tensor(np.random.RandomState(2).randn(1, 3, 32, 32)
                         .astype("float32"))
    v = vgg11(num_classes=7, with_pool=True)
    # 32x32 input → features 1x1; adaptive pool to 7x7 upsamples
    assert v(x).shape == [1, 7]
    m = mobilenet_v2(num_classes=5)
    m.eval()
    assert m(x).shape == [1, 5]
