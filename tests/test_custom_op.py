"""Public custom-op extension point (reference:
paddle/phi/api/ext/op_meta_info.h PD_BUILD_OP + utils/cpp_extension —
test/custom_op/ pattern: register out-of-tree, check fwd/grad/dist)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.utils import (custom_grad, custom_op, custom_spmd_rule,
                              registered_ops)

# -- out-of-tree registration (this test file IS the out-of-tree site) --


@custom_op("testext_swiglu")
def _swiglu(gate, up):
    return jax.nn.silu(gate) * up


@custom_grad("testext_swiglu")
def _swiglu_grad(in_values, out_values, out_grads):
    g, u = in_values
    # single-output ops receive the bare cotangent
    dy = out_grads if not isinstance(out_grads, (tuple, list)) \
        else out_grads[0]
    s = jax.nn.sigmoid(g)
    silu = g * s
    return (dy * u * (s + silu * (1 - s)), dy * silu)


@custom_spmd_rule("testext_swiglu")
def _swiglu_spmd(op, in_tensors, out_vals, args, kwargs):
    from paddle_tpu.distributed.auto_parallel.spmd_rules import _spec_of

    s = _spec_of(in_tensors[0])
    return [s] if s is not None else None


def test_custom_op_forward_and_registry():
    assert "testext_swiglu" in registered_ops()
    r = np.random.RandomState(0)
    g = paddle.to_tensor(r.randn(4, 8).astype("float32"))
    u = paddle.to_tensor(r.randn(4, 8).astype("float32"))
    out = _swiglu(g, u)
    ref = np.asarray(jax.nn.silu(g._value) * u._value)
    np.testing.assert_allclose(np.asarray(out._value), ref, rtol=1e-6)


def test_custom_op_explicit_grad_matches_numeric():
    """OpTest pattern: explicit backward vs numeric differences."""
    r = np.random.RandomState(1)
    gv = r.randn(3, 5).astype("float64").astype("float32")
    uv = r.randn(3, 5).astype("float32")
    g = paddle.to_tensor(gv, stop_gradient=False)
    u = paddle.to_tensor(uv, stop_gradient=False)
    out = _swiglu(g, u)
    loss = paddle.sum(out * out)
    loss.backward()

    def f(gv, uv):
        return float(jnp.sum(jnp.square(jax.nn.silu(gv) * uv)))

    eps = 1e-3
    for t, v, other, first in ((g, gv, uv, True), (u, uv, gv, False)):
        num = np.zeros_like(v)
        it = np.nditer(v, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            vp = v.copy(); vp[idx] += eps
            vm = v.copy(); vm[idx] -= eps
            if first:
                num[idx] = (f(vp, other) - f(vm, other)) / (2 * eps)
            else:
                num[idx] = (f(other, vp) - f(other, vm)) / (2 * eps)
            it.iternext()
        np.testing.assert_allclose(np.asarray(t.grad._value), num,
                                   rtol=2e-2, atol=2e-3)


def test_custom_op_in_sharded_step():
    """The custom op runs inside a compiled SPMD train step."""
    from paddle_tpu import nn
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine

    class TinySwiGLU(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc_g = nn.Linear(8, 16)
            self.fc_u = nn.Linear(8, 16)
            self.out = nn.Linear(16, 4)

        def forward(self, x):
            return self.out(_swiglu(self.fc_g(x), self.fc_u(x)))

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(3)
    model = TinySwiGLU()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(
        lambda m, b: paddle.mean((m(b["x"]) - b["y"]) ** 2))
    r = np.random.RandomState(0)
    batch = {"x": paddle.to_tensor(r.randn(8, 8).astype("float32")),
             "y": paddle.to_tensor(r.randn(8, 4).astype("float32"))}
    first = float(step(batch))
    for _ in range(9):
        last = float(step(batch))
    assert last < first, (first, last)


def test_custom_spmd_rule_propagates():
    from paddle_tpu.distributed.auto_parallel import (ProcessMesh, Shard,
                                                      shard_tensor)

    mesh = ProcessMesh(np.arange(8), dim_names=["mp"])
    g = shard_tensor(np.ones((16, 8), "float32"), mesh, [Shard(0)])
    u = paddle.to_tensor(np.ones((16, 8), "float32"))
    out = _swiglu(g, u)
    assert out.dist_attr is not None and tuple(out.dist_attr)[0] == "mp"


def test_cpp_extension_load(tmp_path):
    """Host-side native extension: compile C++ and call over the C ABI
    (reference utils/cpp_extension.load)."""
    from paddle_tpu.utils import cpp_extension

    src = tmp_path / "ext.cpp"
    src.write_text(
        'extern "C" long long triple(long long x) { return 3 * x; }\n')
    lib = cpp_extension.load("testext_triple", [str(src)],
                             build_directory=str(tmp_path))
    import ctypes

    lib.triple.restype = ctypes.c_longlong
    lib.triple.argtypes = [ctypes.c_longlong]
    assert lib.triple(14) == 42
    # cache hit: second load must not rebuild (same hash -> same file)
    lib2 = cpp_extension.load("testext_triple", [str(src)],
                              build_directory=str(tmp_path))
    assert lib2.triple(1) == 3
