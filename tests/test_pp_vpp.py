"""Circular interleaved virtual-pipeline (vpp>1) — fast unit layer.

Structure, knob plumbing, interleaved segmentation, rng-stream
distinctness, and the named-knob error messages. The compiled-schedule
parity / compile-stability / memory tests live in
tests/test_pipeline_parallel.py (slow marker — they compile pp
programs on the 8-vdev mesh).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (LayerDesc,
                                                        PipelineLayer,
                                                        SegmentLayers)
from paddle_tpu.models import GPTForCausalLMPipe
from paddle_tpu.models.gpt import GPTConfig


def _init_fleet(pp, vpp, dp=1, M=4, micro=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "mp_degree": 1, "pp_degree": pp,
        "pp_configs": {"num_virtual_pipeline_stages": vpp}}
    strategy.pipeline_configs = {"accumulate_steps": M,
                                 "micro_batch_size": micro}
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)
    return fleet.init(is_collective=True, strategy=strategy)


def gpt_tiny(num_layers=4, **kw):
    return GPTConfig(vocab_size=256, hidden_size=64,
                     num_layers=num_layers, num_heads=4,
                     max_position_embeddings=128, **kw)


# ---------------------------------------------------------------------------
# SegmentLayers: interleaved part -> (stage, chunk) assignment
# ---------------------------------------------------------------------------
class TestSegmentInterleave:
    def test_round_robin_part_stage_map(self):
        descs = [LayerDesc(paddle.nn.Linear, 4, 4) for _ in range(8)]
        seg = SegmentLayers(descs, num_parts=2, method="uniform",
                            num_virtual_pipeline_stage=2)
        assert seg.num_parts == 4
        # part j -> stage j % pp during circuit j // pp — interleaved,
        # NOT the reference's contiguous blocks-per-stage
        assert [seg.part_stage(j) for j in range(4)] == [0, 1, 0, 1]
        assert [seg.part_chunk(j) for j in range(4)] == [0, 0, 1, 1]
        assert seg.do_segment() == [0, 2, 4, 6, 8]

    def test_vpp1_is_contiguous_identity(self):
        descs = [LayerDesc(paddle.nn.Linear, 4, 4) for _ in range(8)]
        seg = SegmentLayers(descs, num_parts=4, method="uniform")
        assert [seg.part_stage(j) for j in range(4)] == [0, 1, 2, 3]
        assert [seg.part_chunk(j) for j in range(4)] == [0, 0, 0, 0]

    def test_layer_method_composes_with_vpp(self):
        class Blk(paddle.nn.Layer):
            def __init__(self):
                super().__init__()

        descs = []
        for _ in range(4):
            descs.append(LayerDesc(Blk))
            descs.append(LayerDesc(paddle.nn.Linear, 4, 4))
        seg = SegmentLayers(descs, num_parts=2, method="layer:Blk",
                            num_virtual_pipeline_stage=2)
        # each of the 4 parts starts at a Blk occurrence
        assert seg.do_segment() == [0, 2, 4, 6, 8]
        assert [seg.part_stage(j) for j in range(4)] == [0, 1, 0, 1]

    def test_layer_method_divisibility_error_names_vpp(self):
        class Blk(paddle.nn.Layer):
            def __init__(self):
                super().__init__()

        descs = [LayerDesc(Blk) for _ in range(6)]
        seg = SegmentLayers(descs, num_parts=2, method="layer:Blk",
                            num_virtual_pipeline_stage=2)
        with pytest.raises(Exception, match="num_virtual_pipeline_stages"):
            seg.do_segment()


# ---------------------------------------------------------------------------
# rng streams: distinct per (tick, stage, chunk)
# ---------------------------------------------------------------------------
def test_tick_seed_unique_per_tick_stage_chunk():
    import jax.numpy as jnp

    from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import \
        pp_layers

    base = jnp.uint32(12345)
    seen = set()
    # a realistic large grid: T = vpp*M + S - 1 ticks for S<=8, vpp<=4,
    # M up to 32 -> t < 140
    for t in range(140):
        for s in range(8):
            for v in range(4):
                seed = int(pp_layers._tick_seed(
                    base, jnp.int32(t), jnp.int32(s), jnp.int32(v)))
                assert seed not in seen, (t, s, v)
                seen.add(seed)


# ---------------------------------------------------------------------------
# PipelineLayer structure + knob plumbing
# ---------------------------------------------------------------------------
class TestVppStructure:
    def test_stacked_params_gain_chunk_axis(self):
        _init_fleet(pp=2, vpp=2)
        model = GPTForCausalLMPipe(gpt_tiny(num_layers=8))
        assert model.get_num_virtual_stages() == 2
        sp = model.parameters_in_stacked_blocks
        # [vpp, L/vpp, ...] with the LAYER axis (1) sharded over 'pp'
        assert sp and all(p.shape[0] == 2 and p.shape[1] == 4 for p in sp)
        assert all(tuple(p.dist_attr)[:2] == (None, "pp") for p in sp)

    def test_knob_plumbs_from_strategy_through_hcg(self):
        hcg = _init_fleet(pp=2, vpp=2)
        assert hcg.get_virtual_pipeline_parallel_world_size() == 2
        model = GPTForCausalLMPipe(gpt_tiny())
        assert model._vpp == 2

    def test_explicit_kwarg_overrides_strategy(self):
        _init_fleet(pp=2, vpp=2)
        model = GPTForCausalLMPipe(gpt_tiny(),
                                   num_virtual_pipeline_stages=1)
        assert model._vpp == 1
        sp = model.parameters_in_stacked_blocks
        assert all(tuple(p.dist_attr)[0] == "pp" for p in sp)

    def test_segment_part_stages_interleaved(self):
        _init_fleet(pp=2, vpp=2)
        model = GPTForCausalLMPipe(gpt_tiny(num_layers=8))
        # seg_method="layer:GPTDecoderLayer" composed with vpp
        assert model.segment_parts == [0, 2, 4, 6, 8]
        assert model.segment_part_stages == [0, 1, 0, 1]
        assert model.segment_part_chunks == [0, 0, 1, 1]

    def test_chunk_rows_cover_global_layers_round_robin(self):
        """The [vpp, L/vpp] reshape + axis-1 'pp' sharding IS the
        round-robin chunk->stage map: rank s's chunk v holds global
        layers [v*L/vpp + s*K, v*L/vpp + (s+1)*K)."""
        _init_fleet(pp=2, vpp=2)
        paddle.seed(5)
        L = 4
        cfg = gpt_tiny(num_layers=L)
        flat = GPTForCausalLMPipe(cfg, num_virtual_pipeline_stages=1)
        paddle.seed(5)
        chunked = GPTForCausalLMPipe(cfg)
        for pf, pc in zip(flat.parameters_in_stacked_blocks,
                          chunked.parameters_in_stacked_blocks):
            np.testing.assert_array_equal(
                np.asarray(pf._value),
                np.asarray(pc._value).reshape(pf.shape))


# ---------------------------------------------------------------------------
# named-knob error messages
# ---------------------------------------------------------------------------
class TestVppErrors:
    def test_layers_not_divisible_names_both_knobs(self):
        _init_fleet(pp=2, vpp=4)
        with pytest.raises(Exception) as ei:
            GPTForCausalLMPipe(gpt_tiny(num_layers=6))
        msg = str(ei.value)
        assert "pp_degree (2)" in msg
        assert "num_virtual_pipeline_stages (4)" in msg
        assert "6 layers" in msg

    def test_vpp_without_pipelined_mesh_rejected(self):
        _init_fleet(pp=1, vpp=2)
        with pytest.raises(Exception, match="pp_degree is 1"):
            GPTForCausalLMPipe(gpt_tiny())

    def test_microbatches_not_multiple_of_pp_names_knobs(self):
        _init_fleet(pp=2, vpp=2, M=3)
        model = GPTForCausalLMPipe(gpt_tiny())
        dm = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(paddle.optimizer.SGD(
            learning_rate=0.0, parameters=model.parameters()))
        ids = paddle.to_tensor(np.zeros((3, 16), dtype="int32"))
        with pytest.raises(Exception) as ei:
            dm.train_batch([ids, ids], opt)
        msg = str(ei.value)
        assert "accumulate_steps" in msg and "(3)" in msg
        assert "pp_degree (2)" in msg
        assert "num_virtual_pipeline_stages" in msg

    def test_vpp_zero_or_negative_rejected(self):
        _init_fleet(pp=2, vpp=1)
        with pytest.raises(Exception, match="must be >= 1"):
            GPTForCausalLMPipe(gpt_tiny(),
                               num_virtual_pipeline_stages=-2)


# ---------------------------------------------------------------------------
# observability: the bubble gauge is cataloged with the pp_vpp label
# ---------------------------------------------------------------------------
def test_pp_bubble_gauge_in_catalog_schema():
    import json

    from paddle_tpu.observability import catalog

    with open(catalog.SCHEMA_PATH) as f:
        schema = json.load(f)
    entry = schema["paddle_tpu_train_pp_bubble_fraction"]
    assert entry["type"] == "gauge"
    assert entry["labels"] == ["pp_vpp"]
