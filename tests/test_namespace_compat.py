"""Distributed/incubate/jit/utils API tails (reference: the respective
python/paddle/*/__init__.py export lists)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer

rng = np.random.RandomState(0)


def _t(x):
    return paddle.to_tensor(x)


class TestDistributedCompat:
    def setup_method(self):
        paddle.distributed.init_parallel_env()

    def test_aliases_single_process(self):
        d = paddle.distributed
        assert d.get_backend() == "XLA"
        assert d.is_available() and d.is_initialized()
        t = _t(np.ones(4, "float32"))
        assert d.wait(t) is t
        # rows must divide the world size (8-device CPU mesh harness)
        n = d.get_world_size()
        out = d.alltoall_single(None, _t(np.arange(2 * n,
                                                   dtype="float32")))
        assert out.shape == [2 * n]
        got = []
        d.scatter_object_list(got, list(range(n)))
        assert got == [0]  # rank 0's slice, one object per rank
        gl = []
        d.gather(t, gl, dst=0)
        assert len(gl) == 1

    def test_enums_and_strategy(self):
        d = paddle.distributed
        assert d.ReduceType.kRedSum == 0
        assert d.ParallelMode.TENSOR_PARALLEL == 1
        assert d.ShardingStage2.stage == 2
        st = d.Strategy({"sharding": d.ShardingStage1})
        assert st.sharding is d.ShardingStage1
        da = d.DistAttr(mesh=None, sharding_specs=["x", None])
        assert da.sharding_specs == ["x", None]

    def test_ps_entries_gate(self):
        for cls in (paddle.distributed.InMemoryDataset,
                    paddle.distributed.QueueDataset,
                    paddle.distributed.CountFilterEntry):
            with pytest.raises(NotImplementedError, match="parameter-server"):
                cls()

    def test_modules_exposed(self):
        assert paddle.distributed.io is not None
        assert paddle.distributed.launch is not None
        assert callable(paddle.distributed.save_state_dict)
        assert callable(paddle.distributed.load_state_dict)

    def test_unshard_dtensor(self):
        t = _t(np.ones((2, 2), "float32"))
        out = paddle.distributed.unshard_dtensor(t)
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.ones((2, 2)))

    def test_shard_optimizer_marks(self):
        net = nn.Linear(2, 2)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=net.parameters())
        assert paddle.distributed.shard_optimizer(opt) is opt
        assert opt._shard_states


class TestIncubateTail:
    def test_graph_aliases(self):
        data = _t(np.array([[1.0, 2.0], [3.0, 4.0]], "float32"))
        seg = _t(np.array([0, 0]))
        out = paddle.incubate.segment_sum(data, seg)
        np.testing.assert_allclose(np.asarray(out._value), [[4.0, 6.0]])
        assert callable(paddle.incubate.graph_send_recv)
        assert callable(paddle.incubate.graph_reindex)

    def test_softmax_mask_fuse(self):
        x = _t(rng.randn(2, 4, 4).astype("float32"))
        mask = _t(np.zeros((2, 4, 4), "float32"))
        out = paddle.incubate.softmax_mask_fuse(x, mask)
        np.testing.assert_allclose(np.asarray(out._value).sum(-1),
                                   np.ones((2, 4)), rtol=1e-5)
        ut = paddle.incubate.softmax_mask_fuse_upper_triangle(x)
        o = np.asarray(ut._value)
        assert abs(o[0, 0, 0] - 1.0) < 1e-5 and o[0, 0, 1] < 1e-6

    def test_identity_loss(self):
        x = _t(np.array([1.0, 3.0], "float32"))
        assert float(paddle.incubate.identity_loss(x, "mean")) == 2.0
        assert float(paddle.incubate.identity_loss(x, "sum")) == 4.0

    def test_lookahead_trains(self):
        paddle.seed(0)
        net = nn.Linear(4, 1)
        inner = optimizer.SGD(learning_rate=0.1,
                              parameters=net.parameters())
        la = paddle.incubate.LookAhead(inner, alpha=0.5, k=2)
        X = _t(np.ones((4, 4), "float32"))
        y = _t(np.zeros((4, 1), "float32"))
        first = None
        for _ in range(8):
            loss = ((net(X) - y) ** 2).mean()
            loss.backward()
            la.step()
            la.clear_grad()
            if first is None:
                first = float(loss)
        assert float(loss) < first

    def test_model_average_apply_restore(self):
        paddle.seed(1)
        net = nn.Linear(3, 1)
        ma = paddle.incubate.ModelAverage(parameters=net.parameters())
        w0 = np.asarray(net.weight._value).copy()
        ma.step()
        net.weight._value = net.weight._value * 0
        ma.apply()
        np.testing.assert_allclose(np.asarray(net.weight._value), w0,
                                   rtol=1e-6)
        ma.restore()
        assert np.asarray(net.weight._value).sum() == 0


class TestJitUtilsTail:
    def test_jit_knobs(self):
        paddle.jit.enable_to_static(False)
        try:
            pass
        finally:
            paddle.jit.enable_to_static(True)
        paddle.jit.ignore_module([np])
        paddle.jit.set_code_level(50)
        paddle.jit.set_verbosity(1)

    def test_utils(self):
        assert paddle.utils.try_import("numpy") is np
        with pytest.raises(ImportError, match="nonexistent"):
            paddle.utils.try_import("_nonexistent_module_xyz",
                                    "nonexistent module")
        paddle.utils.require_version("0.0.1")
        with pytest.raises(Exception, match="required"):
            paddle.utils.require_version("99.0.0")

        @paddle.utils.deprecated(update_to="paddle.new_api",
                                 since="0.1.0")
        def old(x):
            return x + 1

        import warnings

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert old(1) == 2
        assert any("deprecated" in str(x.message) for x in w)

    def test_run_check(self):
        assert paddle.utils.run_check()


class TestCompatRegressions:
    def test_dist_model_constructs_and_trains(self):
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = optimizer.AdamW(learning_rate=1e-2,
                              parameters=net.parameters())

        def loss_fn(out, label):
            return nn.functional.cross_entropy(out, label)

        dm = paddle.distributed.to_static(net, None, loss=loss_fn,
                                          optimizer=opt)
        X = _t(np.random.RandomState(0).rand(8, 4).astype("float32"))
        y = _t(np.arange(8) % 2)
        l0 = float(dm(X, y))
        for _ in range(10):
            last = float(dm(X, y))
        assert last < l0
        dm.eval()
        out = dm(X)
        assert out.shape == [8, 2]

    def test_spawn_forks_and_joins(self, tmp_path):
        import os

        marker = str(tmp_path / "rank")

        def worker(path):
            rid = os.environ.get("PADDLE_TRAINER_ID", "?")
            open(path + rid, "w").write(rid)

        paddle.distributed.spawn(worker, args=(marker,), nprocs=2)
        assert sorted(os.listdir(tmp_path)) == ["rank0", "rank1"]

    def test_enable_to_static_toggle(self):
        def f(x):
            return x * 2

        paddle.jit.enable_to_static(False)
        try:
            assert paddle.jit.to_static(f) is f  # eager passthrough
        finally:
            paddle.jit.enable_to_static(True)
        assert paddle.jit.to_static(f) is not f

    def test_lookahead_first_sync_pulls_toward_init(self):
        paddle.seed(2)
        net = nn.Linear(2, 1, bias_attr=False)
        w0 = np.asarray(net.weight._value).copy()
        inner = optimizer.SGD(learning_rate=0.5,
                              parameters=net.parameters())
        la = paddle.incubate.LookAhead(inner, alpha=0.5, k=1)
        X = _t(np.ones((2, 2), "float32"))
        loss = net(X).sum()
        loss.backward()
        w_before_sync = None
        # inner step moves weights; k=1 syncs immediately:
        # new = w0 + 0.5*(w_fast - w0) != w_fast
        la.step()
        w_after = np.asarray(net.weight._value)
        assert not np.allclose(w_after, w0)
        # slow-weight pull means the result is the midpoint, not the
        # raw fast weights: reconstruct fast = w0 - lr*grad
        g = np.ones((2, 1), "float32") * 2  # d(sum(X@w))/dw = col sums
        w_fast = w0 - 0.5 * g
        np.testing.assert_allclose(w_after, w0 + 0.5 * (w_fast - w0),
                                   rtol=1e-5)

    def test_alltoall_single_rejects_uneven_out(self):
        paddle.distributed.init_parallel_env()
        n = paddle.distributed.get_world_size()
        with pytest.raises(Exception, match="out_split_sizes"):
            paddle.distributed.alltoall_single(
                None, _t(np.zeros(2 * n, "float32")),
                out_split_sizes=[1] * n)
