"""Semi-auto parallel (DistTensor) API over the 8-device CPU mesh
(reference: test/auto_parallel/ shard_tensor/reshard API tests)."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def test_process_mesh():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                            dim_names=["x", "y"])
    assert mesh.shape == [2, 4]
    assert mesh.get_dim_size("y") == 4
    assert mesh.process_ids == list(range(8))


def test_shard_tensor_placement():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                            dim_names=["x", "y"])
    x = np.arange(8 * 16, dtype="float32").reshape(8, 16)
    dt = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Shard(1)])
    assert dt.shape == [8, 16]
    np.testing.assert_array_equal(np.asarray(dt._value), x)
    # physically: each device holds an (8/2, 16/4) shard
    shard = dt._value.addressable_shards[0]
    assert shard.data.shape == (4, 4)
    assert str(dt.dist_attr) == str(
        jax.sharding.PartitionSpec("x", "y"))


def test_shard_tensor_replicate():
    mesh = dist.ProcessMesh(list(range(8)), dim_names=["x"])
    x = np.random.RandomState(0).randn(4, 4).astype("float32")
    dt = dist.shard_tensor(x, mesh, [dist.Replicate()])
    assert dt._value.sharding.is_fully_replicated


def test_reshard_changes_layout():
    mesh = dist.ProcessMesh(list(range(8)), dim_names=["x"])
    x = np.random.RandomState(1).randn(8, 8).astype("float32")
    dt = dist.shard_tensor(x, mesh, [dist.Shard(0)])
    assert dt._value.addressable_shards[0].data.shape == (1, 8)
    dt2 = dist.reshard(dt, mesh, [dist.Shard(1)])
    assert dt2._value.addressable_shards[0].data.shape == (8, 1)
    np.testing.assert_array_equal(np.asarray(dt2._value), x)
    dt3 = dist.reshard(dt2, mesh, [dist.Replicate()])
    assert dt3._value.sharding.is_fully_replicated


def test_dtensor_from_fn():
    mesh = dist.ProcessMesh(list(range(8)), dim_names=["x"])
    dt = dist.dtensor_from_fn(paddle.ones, mesh, [dist.Shard(0)], [8, 4])
    assert dt.shape == [8, 4]
    np.testing.assert_array_equal(np.asarray(dt._value), np.ones((8, 4)))


def test_shard_layer_custom_fn():
    mesh = dist.ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
    layer = paddle.nn.Linear(8, 16)

    def shard_fn(name, sub, m):
        for p in sub.parameters(include_sublayers=False):
            if p.ndim == 2:  # weight: shard out dim over mp
                v = dist.shard_tensor(p, m, [dist.Replicate(),
                                             dist.Shard(1)])
                p._value = v._value
                p.dist_attr = v.dist_attr

    dist.shard_layer(layer, mesh, shard_fn)
    assert "mp" in str(layer.weight._value.sharding.spec)
    # forward still works on replicated input
    x = paddle.to_tensor(np.random.RandomState(2).randn(4, 8)
                         .astype("float32"))
    assert layer(x).shape == [4, 16]


def test_shard_tensor_grad_flows():
    """DistTensors participate in autograd like any Tensor."""
    mesh = dist.ProcessMesh(list(range(8)), dim_names=["x"])
    x = np.random.RandomState(3).randn(8, 4).astype("float32")
    dt = dist.shard_tensor(x, mesh, [dist.Shard(0)],
                           stop_gradient=False)
    loss = paddle.sum(dt * dt)
    loss.backward()
    np.testing.assert_allclose(np.asarray(dt.grad._value), 2 * x,
                               rtol=1e-6)
