"""Higher-order autograd: paddle.grad(create_graph=True)
(reference: the eager double_grad node generation of
eager_gen.py + test/legacy_test/test_imperative_double_grad.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import grad


def test_second_derivative_of_cubic():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32"),
                         stop_gradient=False)
    y = x * x * x
    (dx,) = grad(y, x, grad_outputs=paddle.to_tensor(
        np.ones(3, "float32")), create_graph=True)
    np.testing.assert_allclose(np.asarray(dx._value),
                               3 * np.asarray(x._value) ** 2, rtol=1e-5)
    (d2x,) = grad(dx, x, grad_outputs=paddle.to_tensor(
        np.ones(3, "float32")))
    np.testing.assert_allclose(np.asarray(d2x._value),
                               6 * np.asarray(x._value), rtol=1e-5)


def test_mixed_partial():
    """d/dw of dy/dx for y = sin(x) * w equals cos(x)."""
    r = np.random.RandomState(0)
    xv = r.randn(4).astype("float32")
    wv = r.randn(4).astype("float32")
    x = paddle.to_tensor(xv, stop_gradient=False)
    w = paddle.to_tensor(wv, stop_gradient=False)
    y = paddle.sin(x) * w
    (dx,) = grad(y, x, grad_outputs=paddle.to_tensor(
        np.ones(4, "float32")), create_graph=True)
    s = paddle.sum(dx)
    s.backward()
    np.testing.assert_allclose(np.asarray(w.grad._value), np.cos(xv),
                               rtol=1e-5)


def test_gradient_penalty_training_pattern():
    """WGAN-GP shape: gp = mean((dD/dx)^2) backprops into weights;
    checked against numeric differences."""
    from paddle_tpu import nn

    paddle.seed(3)
    m = nn.Linear(3, 1)
    r = np.random.RandomState(1)
    xv = r.randn(5, 3).astype("float32")

    def gp_value(wv):
        # analytic: D(x) = x@w + b -> dD/dx = w; gp = mean over rows of
        # sum_j w_j^2 = ||w||^2
        return float((wv ** 2).sum())

    x = paddle.to_tensor(xv, stop_gradient=False)
    out = m(x)
    (dx,) = grad(out, x, grad_outputs=paddle.to_tensor(
        np.ones((5, 1), "float32")), create_graph=True)
    gp = paddle.mean(paddle.sum(dx * dx, axis=1))
    gp.backward()
    wg = np.asarray(m.weight.grad._value)
    # d gp / d w = 2w (independent of x for a linear D)
    np.testing.assert_allclose(wg, 2 * np.asarray(m.weight._value),
                               rtol=1e-4, atol=1e-5)


def test_second_order_matmul():
    r = np.random.RandomState(2)
    av = r.randn(3, 4).astype("float32")
    a = paddle.to_tensor(av, stop_gradient=False)
    b = paddle.to_tensor(r.randn(4, 2).astype("float32"),
                         stop_gradient=False)
    y = paddle.matmul(a, b)
    (da,) = grad(y, a, grad_outputs=paddle.to_tensor(
        np.ones((3, 2), "float32")), create_graph=True)
    # da = ones @ b.T; d(sum(da * c))/db = ... check via d sum(da)/db
    s = paddle.sum(da)
    (db2,) = grad(s, b)
    # sum(da) = sum(ones @ b.T) = 3 * sum(b) -> d/db = 3
    np.testing.assert_allclose(np.asarray(db2._value),
                               np.full((4, 2), 3.0, "float32"),
                               rtol=1e-5)


def test_custom_node_raises_clearly():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = paddle.to_tensor(np.ones(3, "float32"), stop_gradient=False)
    y = Double.apply(x)
    with pytest.raises(NotImplementedError, match="create_graph"):
        grad(y, x, create_graph=True)


def test_third_order():
    x = paddle.to_tensor(np.array([2.0], "float32"), stop_gradient=False)
    y = x * x * x * x          # x^4
    (d1,) = grad(y, x, create_graph=True)
    (d2,) = grad(d1, x, create_graph=True)
    (d3,) = grad(d2, x)
    np.testing.assert_allclose(np.asarray(d3._value), [48.0], rtol=1e-5)
