"""Predictor / serving API (reference test model:
test/cpp/inference/api/analysis_predictor_tester.cc capabilities — here
the compiled prefill+decode serving loop and the generic Run path)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import Config, GenerationConfig, create_predictor
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(11)
    return LlamaForCausalLM(llama_tiny())


def test_predictor_generate_matches_full_forward(tiny_model):
    """Bucketed prefill + single-program scan decode == greedy argmax
    over repeated full forwards."""
    model = tiny_model
    cfg = model.config
    pred = create_predictor(Config().set_model(model))
    prompt = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 5))
    out = np.asarray(pred.generate(paddle.to_tensor(prompt),
                                   max_new_tokens=6)._value)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(out[:, :5], prompt)

    from paddle_tpu.autograd import no_grad

    cur = prompt
    with no_grad():
        for _ in range(6):
            logits = model(paddle.to_tensor(cur))
            nxt = np.asarray(logits._value)[:, -1].argmax(-1)
            cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, cur)


def test_predictor_prompt_bucketing(tiny_model):
    """Different prompt lengths inside one bucket share one compiled
    prefill program (the serving win vs per-length recompiles)."""
    pred = create_predictor(Config().set_model(tiny_model))
    V = tiny_model.config.vocab_size
    r = np.random.RandomState(1)
    for S0 in (5, 17, 40):  # all bucket to 64
        pred.generate(paddle.to_tensor(r.randint(0, V, (1, S0))),
                      max_new_tokens=2)
    assert len(pred._prefill_fns) == 1
    assert len(pred._decode_fns) == 1


def test_predictor_ragged_lengths(tiny_model):
    """Right-padded ragged batch: each row's first sampled token comes
    from its own true last prompt position."""
    model = tiny_model
    V = model.config.vocab_size
    r = np.random.RandomState(2)
    a = r.randint(0, V, (1, 7))
    b = r.randint(0, V, (1, 4))
    pred = create_predictor(Config().set_model(model))
    batch = np.zeros((2, 7), np.int64)
    batch[0] = a[0]
    batch[1, :4] = b[0]
    out = np.asarray(pred.generate(paddle.to_tensor(batch),
                                   lengths=[7, 4],
                                   max_new_tokens=1)._value)
    # multi-token ragged decode runs at per-row offsets (own rope
    # positions + cache slots); deeper parity in test_paged_ragged.py
    multi = np.asarray(pred.generate(paddle.to_tensor(batch),
                                     lengths=[7, 4],
                                     max_new_tokens=3)._value)
    assert multi.shape == (2, 10)
    # row-wise reference from unbatched full forwards
    from paddle_tpu.autograd import no_grad

    with no_grad():
        la = np.asarray(model(paddle.to_tensor(a))._value)[0, -1].argmax()
        lb = np.asarray(model(paddle.to_tensor(b))._value)[0, -1].argmax()
    assert out[0, -1] == la
    assert out[1, -1] == lb


def test_predictor_sampling_modes(tiny_model):
    """temperature/top-k/top-p compile and produce in-range tokens."""
    pred = create_predictor(Config().set_model(tiny_model))
    V = tiny_model.config.vocab_size
    prompt = np.random.RandomState(3).randint(0, V, (2, 6))
    out = pred.generate(paddle.to_tensor(prompt), max_new_tokens=4,
                        temperature=0.8, top_k=20, top_p=0.9, seed=5)
    out = np.asarray(out._value)
    assert out.shape == (2, 10)
    assert (out >= 0).all() and (out < V).all()


def test_predictor_run_generic(tiny_model):
    """AnalysisPredictor::Run analog: list in, list out, shape-cached."""
    pred = create_predictor(Config().set_model(tiny_model))
    V = tiny_model.config.vocab_size
    x = np.random.RandomState(4).randint(0, V, (2, 8))
    outs = pred.run([paddle.to_tensor(x)])
    assert outs[0].shape == (2, 8, V)
    pred.run([paddle.to_tensor(x)])
    assert len(pred._run_fns) == 1


def test_predictor_load_from_params_file(tmp_path, tiny_model):
    """load → compile → generate from a saved state_dict."""
    p = str(tmp_path / "model.pdparams")
    paddle.save(tiny_model.state_dict(), p)
    cfg = Config(params_file=p)
    cfg.set_model_factory(lambda: LlamaForCausalLM(llama_tiny()))
    pred = create_predictor(cfg)
    V = tiny_model.config.vocab_size
    prompt = np.random.RandomState(5).randint(0, V, (1, 5))
    a = np.asarray(pred.generate(paddle.to_tensor(prompt),
                                 max_new_tokens=3)._value)
    b = np.asarray(create_predictor(Config().set_model(tiny_model))
                   .generate(paddle.to_tensor(prompt),
                             max_new_tokens=3)._value)
    np.testing.assert_array_equal(a, b)


def test_predictor_bucket_clamped_to_cache(tiny_model):
    """Prompt bucket must never exceed the cache length (review
    finding: Sb=_bucket(90)=128 > max_length=100 crashed prefill)."""
    cfg = Config().set_model(tiny_model)
    cfg.max_length = 100
    pred = create_predictor(cfg)
    V = tiny_model.config.vocab_size
    prompt = np.random.RandomState(6).randint(0, V, (1, 90))
    out = pred.generate(paddle.to_tensor(prompt), max_new_tokens=10)
    assert np.asarray(out._value).shape == (1, 100)
