"""hapi Model API (reference: python/paddle/hapi/model.py —
fit/evaluate/predict/save/load + callbacks)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi import EarlyStopping, ModelCheckpoint
from paddle_tpu.io import Dataset


class XorDataset(Dataset):
    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype("float32")
        w = rng.randn(8, 2).astype("float32")
        self.y = (self.x @ w).argmax(-1).astype("int64")

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _network():
    return paddle.nn.Sequential(paddle.nn.Linear(8, 32), paddle.nn.ReLU(),
                                paddle.nn.Linear(32, 2))


def test_fit_evaluate_predict(tmp_path):
    paddle.seed(0)
    model = paddle.Model(_network())
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=model.parameters()),
        loss=paddle.nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())

    ds = XorDataset()
    hist = model.fit(ds, epochs=4, batch_size=16, verbose=0)
    assert hist[-1]["loss"] < hist[0]["loss"]

    logs = model.evaluate(ds, batch_size=16, verbose=0)
    assert logs["eval_acc"] > 0.8, logs

    preds = model.predict(ds, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 2)

    # save / load roundtrip
    p = str(tmp_path / "ck" / "m")
    model.save(p)
    model2 = paddle.Model(_network())
    model2.prepare(
        optimizer=paddle.optimizer.Adam(parameters=model2.parameters()),
        loss=paddle.nn.CrossEntropyLoss())
    model2.load(p)
    for (n, a), (_, b) in zip(model.network.named_parameters(),
                              model2.network.named_parameters()):
        np.testing.assert_array_equal(np.asarray(a._value),
                                      np.asarray(b._value), err_msg=n)


def test_early_stopping_and_checkpoint(tmp_path):
    paddle.seed(1)
    model = paddle.Model(_network())
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=0.0,
                                        parameters=model.parameters()),
        loss=paddle.nn.CrossEntropyLoss())
    ds = XorDataset(32)
    es = EarlyStopping(monitor="loss", patience=1)
    hist = model.fit(ds, epochs=10, batch_size=16, verbose=0,
                     callbacks=[es],
                     save_dir=str(tmp_path / "ckpts"))
    assert model.stop_training and len(hist) < 10
    import os

    assert os.path.exists(str(tmp_path / "ckpts" / "final.pdparams"))


def test_summary():
    model = paddle.Model(_network())
    info = model.summary()
    assert info["total_params"] == 8 * 32 + 32 + 32 * 2 + 2
