"""Llama family: training (TP/engine) + compiled KV-cache generation.

Generation correctness standard: greedy decode with caches must emit the
same tokens as repeated full forwards (the reference validates its fused
decoder against the unfused path the same way)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.models import (LlamaForCausalLM, LlamaPretrainingCriterion,
                               llama_tiny)


def test_llama_forward_and_train_eager():
    cfg = llama_tiny()
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    crit = LlamaPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16))
    losses = []
    for _ in range(8):
        loss = crit(model(paddle.to_tensor(ids)), paddle.to_tensor(ids))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 1.0, losses


def test_llama_gqa_heads():
    cfg = llama_tiny()
    assert cfg.num_kv_heads == 2 and cfg.num_heads == 4
    model = LlamaForCausalLM(cfg)
    out = model(paddle.to_tensor(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 8))))
    assert out.shape == [2, 8, cfg.vocab_size]


def test_gqa_broadcast_matches_repeated_kv():
    """The no-copy GQA paths (broadcast q over [KV, rep]) match the
    materialized repeat_interleave reference exactly — fwd + grad for
    the training attention, fwd for the ragged decode cache path."""
    import jax.numpy as jnp

    import jax
    from paddle_tpu.ops.attention import flash_attention
    from paddle_tpu.ops.pallas.decode_attention import _dense_ragged

    r = np.random.RandomState(3)
    B, S, H, KV, D = 2, 8, 4, 2, 16
    q = jnp.asarray(r.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(r.randn(B, S, KV, D), jnp.float32)
    v = jnp.asarray(r.randn(B, S, KV, D), jnp.float32)

    def rep(t):
        return jnp.repeat(t, H // KV, axis=2)

    fwd = flash_attention.raw(q, k, v, causal=True)
    ref = flash_attention.raw(q, rep(k), rep(v), causal=True)
    np.testing.assert_allclose(np.asarray(fwd), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    gk = jax.grad(lambda kk: flash_attention.raw(
        q, kk, v, causal=True).sum())(k)
    gk_ref = jax.grad(lambda kk: flash_attention.raw(
        q, rep(kk), rep(v), causal=True).sum())(k)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref),
                               rtol=1e-5, atol=1e-5)

    # decode cache path: head-major [B, KV, M, D] caches, ragged offsets
    M = 32
    qd = jnp.asarray(r.randn(B, 1, H, D), jnp.float32)
    kc = jnp.asarray(r.randn(B, KV, M, D), jnp.float32)
    vc = jnp.asarray(r.randn(B, KV, M, D), jnp.float32)
    lens = jnp.asarray([20, 7], jnp.int32)
    out = _dense_ragged(qd, kc, vc, lens)
    ref = _dense_ragged(qd, jnp.repeat(kc, H // KV, axis=1),
                        jnp.repeat(vc, H // KV, axis=1), lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_llama_tp_engine_parity():
    """mp=2 tensor-parallel Llama (GQA kv=2 shards 1 kv head/rank)
    matches single-device training."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    cfg = llama_tiny()
    paddle.seed(5)
    model = LlamaForCausalLM(cfg)
    golden = LlamaForCausalLM(cfg)
    golden.set_state_dict(model.state_dict())
    crit = LlamaPretrainingCriterion(cfg)

    ids = np.random.RandomState(2).randint(0, cfg.vocab_size, (4, 16))

    g_opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=golden.parameters())
    g_losses = []
    for _ in range(2):
        loss = crit(golden(paddle.to_tensor(ids)), paddle.to_tensor(ids))
        loss.backward()
        g_opt.step()
        g_opt.clear_grad()
        g_losses.append(float(loss))

    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
    for i in range(2):
        loss = step({"x": paddle.to_tensor(ids), "y": paddle.to_tensor(ids)})
        np.testing.assert_allclose(float(loss), g_losses[i], rtol=2e-4,
                                   atol=1e-6, err_msg=f"step {i}")


def test_generate_matches_full_forward():
    """Greedy cache decode == greedy argmax over repeated full forwards."""
    cfg = llama_tiny()
    paddle.seed(7)
    model = LlamaForCausalLM(cfg)
    model.eval()
    prompt = np.random.RandomState(3).randint(0, cfg.vocab_size, (2, 5))

    out = model.generate(paddle.to_tensor(prompt), max_new_tokens=6)
    gen = np.asarray(out._value)
    assert gen.shape == (2, 11)
    np.testing.assert_array_equal(gen[:, :5], prompt)

    # reference: re-run the full (uncached) forward each step
    cur = prompt
    from paddle_tpu.autograd import no_grad

    with no_grad():
        for _ in range(6):
            logits = model(paddle.to_tensor(cur))
            nxt = np.asarray(logits._value)[:, -1].argmax(-1)
            cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(gen, cur)


def test_generate_sampling_runs():
    cfg = llama_tiny()
    paddle.seed(9)
    model = LlamaForCausalLM(cfg)
    model.eval()
    prompt = np.random.RandomState(4).randint(0, cfg.vocab_size, (1, 4))
    out = model.generate(paddle.to_tensor(prompt), max_new_tokens=5,
                         temperature=0.8, top_k=10, seed=1)
    assert out.shape == [1, 9]
    assert np.all(np.asarray(out._value) < cfg.vocab_size)


def test_decode_program_reuse():
    """The decode step compiles once and is reused (two cache keys total:
    prefill + decode)."""
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    prompt = np.zeros((1, 4), dtype="int64")
    model.generate(paddle.to_tensor(prompt), max_new_tokens=8)
    assert len(model._decode_fns) == 2
