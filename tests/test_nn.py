"""Layer system tests."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn


def r(*shape):
    return np.random.RandomState(0).rand(*shape).astype(np.float32)


class TestLayerBase:
    def test_parameters_and_naming(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        names = [n for n, _ in m.named_parameters()]
        assert names == ["0.weight", "0.bias", "2.weight", "2.bias"]
        assert len(m.parameters()) == 4

    def test_state_dict_roundtrip(self):
        m1 = nn.Linear(3, 3)
        m2 = nn.Linear(3, 3)
        m2.set_state_dict(m1.state_dict())
        np.testing.assert_array_equal(m1.weight.numpy(), m2.weight.numpy())

    def test_train_eval_dropout(self):
        m = nn.Dropout(0.5)
        x = paddle.to_tensor(r(100))
        m.eval()
        np.testing.assert_array_equal(m(x).numpy(), x.numpy())
        m.train()
        out = m(x).numpy()
        assert (out == 0).any()

    def test_buffers_in_state_dict(self):
        bn = nn.BatchNorm2D(4)
        sd = bn.state_dict()
        assert "_mean" in sd and "_variance" in sd

    def test_forward_hooks(self):
        m = nn.Linear(2, 2)
        calls = []
        h = m.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1))
        m(paddle.to_tensor(r(1, 2)))
        assert calls == [1]
        h.remove()
        m(paddle.to_tensor(r(1, 2)))
        assert calls == [1]

    def test_to_dtype(self):
        m = nn.Linear(2, 2).to(dtype="bfloat16")
        assert str(m.weight.dtype) == "bfloat16"

    def test_apply_and_sublayers(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Sequential(nn.Linear(2, 2)))
        assert len(m.sublayers()) == 3


class TestLayers:
    def test_linear_shape(self):
        m = nn.Linear(5, 7)
        out = m(paddle.to_tensor(r(2, 3, 5)))
        assert out.shape == [2, 3, 7]

    def test_conv_bn_pool(self):
        m = nn.Sequential(
            nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8), nn.ReLU(),
            nn.MaxPool2D(2))
        out = m(paddle.to_tensor(r(2, 3, 8, 8)))
        assert out.shape == [2, 8, 4, 4]

    def test_batchnorm_stats_update(self):
        bn = nn.BatchNorm2D(2, momentum=0.5)
        x = paddle.to_tensor(np.random.randn(4, 2, 3, 3).astype(np.float32) + 5)
        bn.train()
        bn(x)
        assert abs(float(bn._mean.numpy().mean()) - 2.5) < 1.0  # moved toward 5*0.5
        bn.eval()
        m0 = bn._mean.numpy().copy()
        bn(x)
        np.testing.assert_array_equal(bn._mean.numpy(), m0)

    def test_layernorm_rmsnorm(self):
        ln = nn.LayerNorm(8)
        rms = nn.RMSNorm(8)
        x = paddle.to_tensor(np.random.randn(2, 4, 8).astype(np.float32))
        assert ln(x).shape == [2, 4, 8]
        assert rms(x).shape == [2, 4, 8]

    def test_embedding_padding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(paddle.to_tensor(np.array([[0, 1]])))
        assert np.all(out.numpy()[0, 0] == 0)

    def test_mha_self_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.to_tensor(np.random.randn(2, 5, 16).astype(np.float32))
        out = mha(x)
        assert out.shape == [2, 5, 16]

    def test_mha_cache_decode(self):
        mha = nn.MultiHeadAttention(16, 4)
        mha.eval()
        x = paddle.to_tensor(np.random.randn(1, 1, 16).astype(np.float32))
        cache = mha.gen_cache(x)
        out, cache = mha(x, x, x, None, cache)
        assert cache.k.shape[1] == 1
        out, cache = mha(x, x, x, None, cache)
        assert cache.k.shape[1] == 2

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32)
        enc = nn.TransformerEncoder(layer, 2)
        x = paddle.to_tensor(np.random.randn(2, 6, 16).astype(np.float32))
        assert enc(x).shape == [2, 6, 16]

    def test_loss_layers(self):
        ce = nn.CrossEntropyLoss()
        loss = ce(paddle.to_tensor(np.random.randn(4, 5).astype(np.float32)),
                  paddle.to_tensor(np.array([0, 1, 2, 3])))
        assert loss.shape == []
        mse = nn.MSELoss()
        out = mse(paddle.to_tensor(r(3)), paddle.to_tensor(r(3)))
        assert float(out.numpy()) >= 0


class TestSaveLoad:
    def test_save_load_roundtrip(self, tmp_path):
        m = nn.Linear(4, 4)
        path = str(tmp_path / "model.pdparams")
        paddle.save(m.state_dict(), path)
        loaded = paddle.load(path)
        m2 = nn.Linear(4, 4)
        m2.set_state_dict(loaded)
        np.testing.assert_array_equal(m.weight.numpy(), m2.weight.numpy())
