"""Multi-process runtime: 2-process CPU pod with loss parity vs 1 process.

The reference proves its distributed stack with single-host multi-process
jobs asserting loss parity against a local run (TestDistBase,
test/legacy_test/test_dist_base.py:959 + _run_cluster_gloo:1555). Here:
one pod of 2 CPU processes joins one jax runtime via
jax.distributed.initialize (bootstrapped over the native TCPStore), runs
the ParallelEngine dp=2 train step — XLA collectives crossing the
process boundary over gloo — and must produce the same losses as a
single process with a dp=2 in-process mesh on the same global batch.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_WORKER = os.path.join(_REPO, "tests", "workers", "mp_gpt_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env():
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("PADDLE_", "JAX_", "XLA_")):
            del env[k]
    return env


def _run_pod(world, dp, ndev_per_proc, out, timeout=600):
    port = _free_port()
    procs = []
    for rank in range(world):
        env = _clean_env()
        env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                            f"{ndev_per_proc} "
                            "--xla_cpu_multi_thread_eigen=false "
                            "intra_op_parallelism_threads=1")
        env["JAX_PLATFORMS"] = "cpu"
        # thread caps: world x ndev XLA runtimes on a shared CI box
        # oversubscribe wildly otherwise (round-4 flake source)
        env["OMP_NUM_THREADS"] = "1"
        env["OPENBLAS_NUM_THREADS"] = "1"
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS_NUM"] = str(world)
        env["PADDLE_MASTER"] = f"127.0.0.1:{port}"
        env["TEST_DP"] = str(dp)
        env["TEST_OUT"] = out
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER], env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    fail = []
    for rank, p in enumerate(procs):
        try:
            out_bytes, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            from utils import kill_and_reap

            kill_and_reap(procs)
            raise
        if p.returncode != 0:
            fail.append((rank, p.returncode,
                         out_bytes.decode(errors="replace")[-4000:]))
    assert not fail, f"worker failures: {fail}"
    results = {}
    for rank in range(world):
        with open(f"{out}.{rank}") as f:
            results[rank] = json.load(f)
    return results


def test_two_process_dp_loss_parity(tmp_path):
    # one retry PER POD: the 2-proc bootstrap can starve past the
    # worker timeout (or die on an internal bootstrap timeout, which
    # surfaces as the worker-failure AssertionError) when the shared CI
    # box runs several suites at once — observed clean alone, one
    # timeout in 10 under 4-way load; same guard test_rpc uses
    def pod_with_retry(tag, **kw):
        try:
            return _run_pod(out=str(tmp_path / tag), **kw)
        except (subprocess.TimeoutExpired, AssertionError):
            return _run_pod(out=str(tmp_path / (tag + "_retry")), **kw)

    ref = pod_with_retry("ref", world=1, dp=2, ndev_per_proc=2)
    two = pod_with_retry("two", world=2, dp=2, ndev_per_proc=1)
    ref_losses = ref[0]["losses"]
    for rank in (0, 1):
        np.testing.assert_allclose(two[rank]["losses"], ref_losses,
                                   rtol=2e-5, atol=2e-6,
                                   err_msg=f"rank {rank} loss diverged")
    # host-side collectives crossed the process boundary
    assert two[0]["gathered"] == [{"rank": 0, "tag": "hello"},
                                  {"rank": 1, "tag": "hello"}]
    assert two[1]["gathered"] == two[0]["gathered"]
    assert two[0]["bcast"] == {"payload": 123}
    assert two[1]["bcast"] == {"payload": 123}
    assert two[1]["recv"] == [1.0, 2.0, 3.0, 4.0]
