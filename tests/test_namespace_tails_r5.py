"""Round-5 namespace tails: distributed.stream, P2POp/batch_isend_irecv,
fleet role makers + fleet.util, audio.datasets (TESS/ESC50 with a
native PCM16 WAV parser).

Reference: communication/stream/*, communication/batch_isend_irecv.py,
fleet/base/role_maker.py:654/1163 + util_factory.py,
audio/datasets/{tess.py:36, esc50.py:41}.
"""
import os
import struct
import tempfile
import zipfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


def _wav_bytes(sr=16000, n=100, amp=20000):
    pcm = (np.sin(np.linspace(0, 10, n)) * amp).astype("<i2").tobytes()
    hdr = b"RIFF" + struct.pack("<I", 36 + len(pcm)) + b"WAVE"
    fmt = b"fmt " + struct.pack("<IHHIIHH", 16, 1, 1, sr, sr * 2, 2, 16)
    return hdr + fmt + b"data" + struct.pack("<I", len(pcm)) + pcm


class TestDistributedTails:
    def test_stream_namespace(self):
        x = paddle.to_tensor(np.ones(4, "float32"))
        out = dist.stream.all_reduce(x, use_calc_stream=True)
        assert out is not None
        # single process: broadcast/reduce are identities
        assert np.allclose(
            np.asarray(dist.stream.broadcast(x, src=0)._value), 1.0)

    def test_p2pop_batch(self):
        x = paddle.to_tensor(np.arange(4, dtype="float32"))
        ops = [dist.P2POp(dist.isend, x, 0),
               dist.P2POp(dist.irecv, x, 0)]
        tasks = dist.batch_isend_irecv(ops)
        assert len(tasks) == 2
        for t in tasks:
            t.wait()
        import pytest

        with pytest.raises(Exception):
            dist.P2POp(dist.all_reduce, x, 0)  # only isend/irecv

    def test_role_makers(self):
        rm = fleet.PaddleCloudRoleMaker(is_collective=True)
        assert rm.is_worker() and not rm.is_server()
        assert rm.worker_index() == int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        u = fleet.UserDefinedRoleMaker(current_id=3, worker_num=8)
        assert u.worker_index() == 3 and u.worker_num() == 8
        assert not u.is_first_worker()
        fleet.init(role_maker=rm, is_collective=True)

    def test_fleet_util(self):
        assert isinstance(fleet.util, fleet.UtilBase)
        files = ["a", "b", "c", "d", "e"]
        assert fleet.util.get_file_shard(files) == files  # world=1
        assert np.allclose(fleet.util.all_reduce(np.ones(3), "sum"),
                           np.ones(3))


class TestAudioDatasets:
    def test_wav_parser_and_tess(self):
        from paddle_tpu.audio.datasets import TESS

        with tempfile.TemporaryDirectory() as d:
            for nm in ("OAF_back_angry.wav", "YAF_dog_happy.wav",
                       "notes.txt"):
                with open(os.path.join(d, nm), "wb") as f:
                    f.write(_wav_bytes() if nm.endswith(".wav")
                            else b"x")
            ds = TESS(d)
            assert len(ds) == 2
            w, y = ds[0]
            assert w.dtype == np.float32
            assert abs(float(np.abs(w).max()) - 20000 / 32768) < 0.05
            assert int(y) == TESS.EMOTIONS.index("angry")

    def test_esc50_folds_and_zip(self):
        from paddle_tpu.audio.datasets import ESC50

        with tempfile.TemporaryDirectory() as d:
            for nm in ("1-100032-A-0.wav", "5-9032-A-14.wav"):
                with open(os.path.join(d, nm), "wb") as f:
                    f.write(_wav_bytes())
            tr = ESC50(d, mode="train")
            dv = ESC50(d, mode="dev")
            assert len(tr) == 1 and int(tr[0][1]) == 0
            assert len(dv) == 1 and int(dv[0][1]) == 14
            zp = os.path.join(d, "esc.zip")
            with zipfile.ZipFile(zp, "w") as z:
                z.writestr("audio/1-1-A-3.wav", _wav_bytes())
            z2 = ESC50(zp, mode="train")
            assert len(z2) == 1 and int(z2[0][1]) == 3

    def test_non_pcm_gates(self):
        from paddle_tpu.audio.datasets import _read_wav

        import pytest

        with pytest.raises(NotImplementedError):
            _read_wav(b"OggS" + b"\x00" * 40)
