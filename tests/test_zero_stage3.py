"""ZeRO stage-3 parameter sharding with the T3-style bucketed
just-in-time gather (distributed/grad_buckets.py BucketPlan.gather +
the engine integration).

Under test:
- the strategy knob surface: sharding_configs["sharding_stage"] = 3
  stores every plan entry's param shard-only (engine._ZeroPlan
  store_sharded) with no group_sharded_parallel call needed
- stage-3 vs stage-2 loss/param BIT-parity on the 8-vdev mesh: flat
  ZeRO MLP (dp2 x sharding4) and the gpt13b smoke topology
  (mp2 x pp2 x sharding2, vpp2), incl. AMP GradScaler and quant_comm
  int8 on — the gather is pure data movement, so the trajectories
  must coincide exactly
- per-device model-state bytes at EXACTLY 1/sharding_degree: measured
  accounting == closed form byte-for-byte (memledger)
- comm-ledger gather exactness: all_gather bytes on the sharding axis
  == (p-1) x stored shard bytes closed form; the seam gather rides
  the lax.scan with trips=nb (scan_trips); bucketed vs per-param
  gather (stage3_release_after_forward) moves identical bytes through
  a different node count
- zero steady-state recompiles on every stage-3 program
- checkpoint: stage-3 shard-only save + bit-exact resume, reshard
  across stage 2<->3 and across sharding degrees, and the flagship
  5+crash+5 == 10-straight gate on the gpt13b smoke topology
- auto_tuner: sharding_stage=3 in the search space, priced by the
  memory/cost models
- tpulint: grad_buckets + the stage-3 engine paths at zero baseline
  entries
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import grad_buckets as gb
from paddle_tpu.distributed.engine import ParallelEngine
from paddle_tpu.observability import memledger as ml


def _reset_fleet():
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)


def _mlp():
    class MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(16, 32)
            self.fc2 = paddle.nn.Linear(32, 16)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    return MLP()


def _loss_fn(model, batch):
    return paddle.mean((model(batch["x"]) - batch["y"]) ** 2)


def _flat_engine(stage, overlap=True, release=True, quant="none",
                 amp=False, level="os_g", dp=2, sh=4, steps=3):
    """dp x sharding ZeRO MLP engine with the stage knob on the
    strategy (the reference hybrid_configs plumbing)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "sharding_degree": sh,
        "sharding_configs": {"comm_overlap": overlap,
                             "comm_buffer_size_MB": 0.0005,
                             "sharding_stage": stage,
                             "stage3_release_after_forward": release},
        "quant_comm": {"dtype": quant, "chunk": 32}}
    _reset_fleet()
    hcg = fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(3)
    model = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    if level:
        model, opt, _ = dist.group_sharded_parallel(model, opt, level)
    eng = ParallelEngine(model, opt, hcg.mesh)
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10) \
        if amp else None
    step = eng.train_step(_loss_fn, scaler=scaler)
    np.random.seed(0)
    x = np.random.randn(8, 16).astype("float32")
    y = np.random.randn(8, 16).astype("float32")
    batch = {"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}
    losses = [float(step(batch)) for _ in range(steps)]
    eng._flush_pending_scalars()
    return eng, model, losses, batch, step


def _covered_shard_bytes(eng):
    return sum(ml.shard_bytes(p._value) for p in eng.trainable
               if eng._zero.entry(p) is not None
               and eng._zero.entry(p)[1])


# ---------------------------------------------------------------------------
# the strategy knob surface
# ---------------------------------------------------------------------------
def test_strategy_defaults_carry_stage_knobs():
    s = fleet.DistributedStrategy()
    sc = s.hybrid_configs["sharding_configs"]
    assert sc["sharding_stage"] == 2
    assert sc["stage3_release_after_forward"] is True
    s.hybrid_configs = {"sharding_configs": {"sharding_stage": 3}}
    sc = s.hybrid_configs["sharding_configs"]
    assert sc["sharding_stage"] == 3
    assert sc["stage3_release_after_forward"] is True
    assert gb.stage_config(s) == (3, True)


def test_knob_flips_storage_without_group_sharded_call():
    """sharding_stage=3 alone (no group_sharded_parallel) stores every
    plan entry's param scattered over 'sharding'."""
    eng, _, _, _, _ = _flat_engine(3, level=None)
    assert eng._sharding_stage == 3
    entries = [eng._zero.entry(p) for p in eng.trainable]
    assert entries and all(e is not None and e[1] for e in entries)
    for p in eng.trainable:
        assert "sharding" in str(eng._zero.storage_spec(p))


# ---------------------------------------------------------------------------
# flat parity: stage-3 == stage-2, bit-on
# ---------------------------------------------------------------------------
class TestFlatParity:
    def test_stage3_bit_parity_and_compile_stability(self):
        eng2, m2, l2, _, _ = _flat_engine(2)
        eng3, m3, l3, batch, step = _flat_engine(3)
        # the gather is exact data movement: the loss trajectory
        # coincides bit-on (same values through the same grad path)
        assert l3 == l2
        # params: stage 2 and stage 3 are different XLA programs, so
        # elementwise-update fusion may differ by an ulp — the repo's
        # parity gate (<= 1e-5, the bench _EXACT bound) applies
        for p2, p3 in zip(m2.parameters(), m3.parameters()):
            np.testing.assert_allclose(np.asarray(p3._value),
                                       np.asarray(p2._value),
                                       rtol=0, atol=1e-5)
        assert eng3.stats.compiles == 1
        float(step(batch))
        assert eng3.stats.compiles == 1

    def test_amp_scaler_parity(self):
        _, _, l2, _, _ = _flat_engine(2, amp=True)
        eng3, _, l3, _, _ = _flat_engine(3, amp=True)
        assert l3 == l2
        assert eng3.stats.compiles == 1

    def test_p_g_os_level_uses_bucketed_gather(self):
        """group_sharded_parallel "p_g_os" rides the same bucketed
        gather when the comm_overlap plan exists."""
        eng, _, losses, _, _ = _flat_engine(2, level="p_g_os")
        assert all(np.isfinite(losses))
        led = eng.comm_ledger()
        plan = eng._bucket_plan
        rs_buckets = sum(len(g.buckets) for g in plan.groups
                        if g.kind == "rs")
        assert led.ops_for(axis="sharding", op="all_gather") == rs_buckets

    def test_memory_at_one_over_sharding_degree(self):
        eng2, _, _, _, _ = _flat_engine(2)
        eng3, _, _, _, _ = _flat_engine(3)
        a2 = ml.account_engine(eng2)
        a3 = ml.account_engine(eng3)
        c3 = ml.closed_form_state_bytes(eng3)
        # measured == closed form byte-for-byte (shard_shape path vs
        # global-shape/degree path)
        for k, v in c3.items():
            assert a3.components.get(k) == v, k
        # every MLP param is plan-covered: the whole params component
        # sits at exactly 1/sharding_degree of the stage-2 image
        assert a3.components["params"] * 4 == a2.components["params"]
        # optimizer state was already stage-2 scattered — unchanged
        assert a3.components["optimizer_state"] == \
            a2.components["optimizer_state"]


# ---------------------------------------------------------------------------
# ledger exactness: gather bytes + the release knob's node granularity
# ---------------------------------------------------------------------------
class TestGatherLedger:
    def test_gather_bytes_closed_form_and_bucketed_ops(self):
        eng, _, _, _, _ = _flat_engine(3)
        led = eng.comm_ledger()
        closed = (4 - 1) * _covered_shard_bytes(eng)
        assert led.bytes_for(axis="sharding", op="all_gather") == closed
        # bucketed: one coalesced gather per rs bucket, not per param
        plan = eng._bucket_plan
        rs_buckets = sum(len(g.buckets) for g in plan.groups
                        if g.kind == "rs")
        n_covered = sum(1 for p in eng.trainable
                        if eng._zero.entry(p) is not None
                        and eng._zero.entry(p)[1])
        assert led.ops_for(axis="sharding", op="all_gather") \
            == rs_buckets < n_covered

    def test_release_knob_off_gathers_per_param_same_bytes(self):
        eng_on, _, l_on, _, _ = _flat_engine(3, release=True)
        eng_off, _, l_off, _, _ = _flat_engine(3, release=False)
        # identical data movement -> identical trajectory
        assert l_on == l_off
        led_on, led_off = eng_on.comm_ledger(), eng_off.comm_ledger()
        assert led_on.bytes_for(axis="sharding", op="all_gather") == \
            led_off.bytes_for(axis="sharding", op="all_gather")
        n_covered = sum(1 for p in eng_off.trainable
                        if eng_off._zero.entry(p) is not None
                        and eng_off._zero.entry(p)[1])
        assert led_off.ops_for(axis="sharding", op="all_gather") \
            == n_covered
        assert led_on.ops_for(axis="sharding", op="all_gather") \
            < n_covered

    def test_no_overlap_plan_falls_back_per_param(self):
        eng, _, losses, _, _ = _flat_engine(3, overlap=False)
        assert eng._bucket_plan is None
        assert all(np.isfinite(losses))
        led = eng.comm_ledger()
        closed = (4 - 1) * _covered_shard_bytes(eng)
        assert led.bytes_for(axis="sharding", op="all_gather") == closed


# ---------------------------------------------------------------------------
# quant_comm composition: int8 wire + own-shard splice at bucket grain
# ---------------------------------------------------------------------------
class TestQuantComposition:
    def test_stage3_equals_stage2_under_quant(self):
        """With quant_comm's param_gather on, stage 2 already stores
        shards (PR-14 store_sharded) — stage 3 is the SAME program, so
        the trajectories must be identical floats."""
        eng2, _, l2, _, _ = _flat_engine(2, quant="int8")
        eng3, _, l3, _, _ = _flat_engine(3, quant="int8")
        assert l3 == l2
        assert eng3.stats.compiles == 1

    def test_quant_tracks_fp32_and_residuals_exist(self):
        _, _, l_fp, _, _ = _flat_engine(3)
        eng_q, _, l_q, _, _ = _flat_engine(3, quant="int8", steps=6)
        gap = max(abs(a - b) for a, b in zip(l_fp, l_q))
        assert gap < 5e-3
        assert eng_q._quant_residuals
        led = eng_q.comm_ledger()
        # the bucketed quantized gather stamps its compression ratio
        ag = [r for r in led.records
              if r.axis == "sharding" and r.op == "all_gather"]
        assert ag and all(r.payload_ratio < 1.0 for r in ag)


# ---------------------------------------------------------------------------
# the gpt13b smoke topology: mp2 x pp2 x sharding2, vpp2 (seam scan)
# ---------------------------------------------------------------------------
def _gpt_pipe(stage, quant="none", amp=False, vpp=2, lr=1e-3, steps=3):
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_position_embeddings=32)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
        "sharding_degree": 2,
        "mp_configs": {"mp_async_allreduce": True},
        "pp_configs": {"num_virtual_pipeline_stages": vpp},
        "sharding_configs": {"comm_overlap": True,
                             "comm_buffer_size_MB": 0.001,
                             "sharding_stage": stage},
        "quant_comm": {"dtype": quant, "chunk": 64}}
    strategy.sharding_configs = {"stage": stage}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    _reset_fleet()
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    model = GPTForCausalLMPipe(cfg)
    dm = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(
        paddle.optimizer.AdamW(learning_rate=lr,
                               parameters=model.parameters()))
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10) \
        if amp else None
    r = np.random.RandomState(0)
    ids = r.randint(0, cfg.vocab_size, (8, 17))
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])
    losses = [float(dm.train_batch([x, y], opt, scaler=scaler))
              for _ in range(steps)]
    return dm, model, opt, (x, y), losses


class TestGptSeamParity:
    def test_stage3_bit_parity_vpp2(self):
        _, m2, _, _, l2 = _gpt_pipe(2)
        dm3, m3, _, _, l3 = _gpt_pipe(3)
        assert l3 == l2
        for p2, p3 in zip(m2.parameters(), m3.parameters()):
            np.testing.assert_array_equal(np.asarray(p3._value),
                                          np.asarray(p2._value))
        eng = dm3._engine
        assert eng.stats.compiles == 1
        # the stacked decoder chunks gather through the seam scan:
        # trips=nb all_gather records on the sharding axis
        led = eng.comm_ledger()
        ag = [r for r in led.records
              if r.axis == "sharding" and r.op == "all_gather"]
        assert any(r.trips > 1 for r in ag)
        closed = (2 - 1) * _covered_shard_bytes(eng)
        assert led.bytes_for(axis="sharding", op="all_gather") == closed

    def test_stage3_memory_closed_form_gpt(self):
        dm2, _, _, _, _ = _gpt_pipe(2)
        dm3, _, _, _, _ = _gpt_pipe(3)
        e2, e3 = dm2._engine, dm3._engine
        a2 = ml.account_engine(e2, batch_tokens=8 * 16,
                               accumulate_steps=2)
        a3 = ml.account_engine(e3, batch_tokens=8 * 16,
                               accumulate_steps=2)
        c3 = ml.closed_form_state_bytes(e3)
        for k, v in c3.items():
            assert a3.components.get(k) == v, k
        # stage 2 stores the same plan entries REPLICATED over
        # 'sharding' — the stage-3 storage shrinks exactly those by
        # the sharding degree and leaves non-plan params untouched
        planned2 = sum(ml.shard_bytes(p._value) for p in e2.trainable
                       if e2._zero.entry(p) is not None)
        uncovered3 = a3.components["params"] - _covered_shard_bytes(e3)
        uncovered2 = a2.components["params"] - planned2
        assert uncovered3 == uncovered2
        assert _covered_shard_bytes(e3) * 2 == planned2

    @pytest.mark.slow
    def test_stage3_amp_and_quant_parity(self):
        _, _, _, _, l2a = _gpt_pipe(2, amp=True)
        _, _, _, _, l3a = _gpt_pipe(3, amp=True)
        assert l3a == l2a
        _, _, _, _, l2q = _gpt_pipe(2, quant="int8")
        dm3q, _, _, _, l3q = _gpt_pipe(3, quant="int8")
        assert l3q == l2q
        assert dm3q._engine.stats.compiles == 1


# ---------------------------------------------------------------------------
# checkpoint: shard-only save, reshard-on-load, crash+resume
# ---------------------------------------------------------------------------
class TestCheckpoint:
    def test_stage3_save_resume_bit_exact(self, tmp_path):
        _, _, straight, _, _ = _flat_engine(3, steps=6)
        eng1, _, first, batch, step = _flat_engine(3, steps=3)
        assert first == straight[:3]
        path = str(tmp_path / "ck")
        eng1.save_checkpoint(path)
        eng2, _, _, batch2, step2 = _flat_engine(3, steps=1)
        eng2.restore_checkpoint(path)
        rest = [float(step2(batch2)) for _ in range(3)]
        assert rest == straight[3:]

    def test_stage3_save_is_shard_only(self, tmp_path):
        """Every saved model-param shard is 1/sharding_degree of the
        global shape along its scatter dim — nobody writes (or holds)
        a full stage-3 parameter image."""
        import glob
        import json
        import os

        eng, model, _, _, _ = _flat_engine(3)
        path = str(tmp_path / "ck")
        eng.save_checkpoint(path)
        meta_file = glob.glob(os.path.join(path, "*.metadata"))[0]
        with open(meta_file) as f:
            md = json.load(f)
        dims = {id(p): eng._zero.entry(p)[0] for p in eng.trainable}
        names = {id(p): n for n, p in model.named_parameters()}
        for p in eng.trainable:
            key = f"model.{names[id(p)]}"
            gshape = md["global_shape"][key]
            d = dims[id(p)]
            for m in md["state_dict_metadata"][key]:
                assert m["local_shape"][d] == gshape[d] // 4

    def test_reshard_stage3_to_stage2_and_back(self, tmp_path):
        eng3, m3, _, _, _ = _flat_engine(3)
        p3 = str(tmp_path / "ck3")
        eng3.save_checkpoint(p3)
        # stage-3 shards load into a stage-2 (replicated-storage)
        # engine: the loader reassembles windows per target sharding
        eng2, m2, _, batch2, step2 = _flat_engine(2, steps=1)
        eng2.restore_checkpoint(p3)
        for pa, pb in zip(m3.parameters(), m2.parameters()):
            np.testing.assert_array_equal(np.asarray(pa._value),
                                          np.asarray(pb._value))
        float(step2(batch2))    # restored engine still steps
        # and a stage-2 checkpoint restores into stage-3 storage
        p2 = str(tmp_path / "ck2")
        eng2.save_checkpoint(p2)
        eng3b, m3b, _, batch3, step3 = _flat_engine(3, steps=1)
        eng3b.restore_checkpoint(p2)
        for pa, pb in zip(m2.parameters(), m3b.parameters()):
            np.testing.assert_array_equal(np.asarray(pa._value),
                                          np.asarray(pb._value))
        float(step3(batch3))

    def test_reshard_across_sharding_degrees(self, tmp_path):
        eng4, m4, _, _, _ = _flat_engine(3, dp=2, sh=4)
        path = str(tmp_path / "ck")
        eng4.save_checkpoint(path)
        eng2, m2, _, batch, step = _flat_engine(3, dp=4, sh=2, steps=1)
        eng2.restore_checkpoint(path)
        for pa, pb in zip(m4.parameters(), m2.parameters()):
            np.testing.assert_array_equal(np.asarray(pa._value),
                                          np.asarray(pb._value))
        float(step(batch))

    @pytest.mark.slow
    def test_5_crash_5_equals_10_straight_gpt(self, tmp_path):
        """The flagship gate on the gpt13b smoke topology: 5 steps +
        save + restore into a fresh stage-3 engine + 5 more == 10
        straight, bit-exactly — shard-only params, scattered moments,
        RNG and counters all round-trip in one commit unit."""
        dm, _, opt, (x, y), straight = _gpt_pipe(3, steps=10)
        dm1, _, opt1, (x1, y1), first = _gpt_pipe(3, steps=5)
        assert first == straight[:5]
        path = str(tmp_path / "ck")
        dm1.save_checkpoint(path)
        dm2, _, opt2, (x2, y2), _ = _gpt_pipe(3, steps=0)
        dm2.restore_checkpoint(path, optimizer=opt2)
        rest = [float(dm2.train_batch([x2, y2], opt2))
                for _ in range(5)]
        assert rest == straight[5:]


# ---------------------------------------------------------------------------
# auto_tuner: stage 3 in the search space, priced by the models
# ---------------------------------------------------------------------------
class TestAutoTuner:
    MODEL = {"hidden_size": 768, "num_layers": 12, "num_heads": 12,
             "vocab_size": 50304}

    def test_stage3_in_default_candidates(self):
        from paddle_tpu.distributed.auto_tuner import default_candidates

        cands = default_candidates(8, self.MODEL, global_batch=32)
        s3 = [c for c in cands if c.get("sharding_stage") == 3]
        assert s3 and all(c["sharding_degree"] > 1 for c in s3)
        # sharding-free configs never carry the stage knob
        assert all(c.get("sharding_stage") != 3 for c in cands
                   if c["sharding_degree"] == 1)

    def test_models_price_stage3(self):
        from paddle_tpu.distributed.auto_tuner import (
            estimate_memory_gb, estimate_step_time)

        base = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                "sharding_degree": 8, "micro_batch_size": 4}
        s3 = dict(base, sharding_stage=3)
        # stage 3 trades HBM (params+grads / sh) for gather comm
        assert estimate_memory_gb(self.MODEL, s3, 32, 1024) < \
            estimate_memory_gb(self.MODEL, base, 32, 1024)
        assert estimate_step_time(self.MODEL, s3, 32, 1024) > \
            estimate_step_time(self.MODEL, base, 32, 1024)

    def test_crosscheck_prices_stage3_consistently(self):
        """AutoTuner.crosscheck on the measured stage-3 footprint: the
        stage-3 analytic estimate must sit BELOW the stage-2 one for
        the same measured bytes (params+grads / sharding_degree), so
        the measured-vs-analytic loop ranks the stages on their real
        trade instead of pruning stage 3 on stage-2 arithmetic."""
        from paddle_tpu.distributed.auto_tuner import AutoTuner

        dm3, _, _, _, _ = _gpt_pipe(3)
        eng = dm3._engine
        acct = ml.account_engine(eng, batch_tokens=8 * 16,
                                 accumulate_steps=2)
        assert acct.measured_bytes > 0 and acct.analytic_bytes > 0
        tuner = AutoTuner({"hidden_size": 32, "num_layers": 4,
                           "num_heads": 4, "vocab_size": 128},
                          num_devices=8, global_batch=4, seq_len=32)
        cfg = {"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
               "sharding_degree": 2, "micro_batch_size": 1}
        m_gb = acct.measured_bytes / 1e9
        d3 = tuner.crosscheck(dict(cfg, sharding_stage=3), m_gb)
        d2 = tuner.crosscheck(dict(cfg, sharding_stage=2), m_gb)
        assert d3 < d2
        # the live gauge's derivation (account_engine) uses the same
        # analytic model: stage-3 analytic bytes drop vs a stage-2
        # config of identical geometry
        from paddle_tpu.distributed.auto_tuner import estimate_memory_gb

        assert estimate_memory_gb(
            tuner.model, dict(cfg, sharding_stage=3), 4, 32) < \
            estimate_memory_gb(
                tuner.model, dict(cfg, sharding_stage=2), 4, 32)


# ---------------------------------------------------------------------------
# the stage-3 custom VJP: mirrored gather/reduce-scatter pairing
# ---------------------------------------------------------------------------
def test_stage3_gather_vjp_is_mirrored_reduce_scatter():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed.engine import _shard_map
    from paddle_tpu.observability import commledger as cl

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("s",))

    def f(x):
        full = gb.stage3_gather(x, "s")
        return jnp.sum(full * full)

    def run(x):
        def body(xl):
            val, vjp = jax.vjp(f, xl)
            (g,) = vjp(jnp.float32(1.0))
            return g

        return jax.jit(_shard_map(body, mesh, (P("s"),), P("s")))(x)

    x = np.arange(16, dtype=np.float32)
    with cl.capture() as cap:
        g = run(x)
    # d/dx sum(gather(x)^2) = 2x on every rank summed -> 2*p*x
    np.testing.assert_allclose(np.asarray(g), 2 * 8 * x, rtol=1e-6)
    ops = {r.op for r in cap.records}
    assert "all_gather" in ops and "reduce_scatter" in ops


# ---------------------------------------------------------------------------
# tpulint: the bidirectional engine paths stay clean, zero baseline
# ---------------------------------------------------------------------------
def test_tpulint_stage3_surface_zero_baseline():
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo))
    try:
        from tools.tpulint import ALL_RULES, lint_paths

        findings = lint_paths(
            [repo / "paddle_tpu" / "distributed" / "grad_buckets.py",
             repo / "paddle_tpu" / "distributed" / "engine.py"],
            ALL_RULES, root=repo)
    finally:
        sys.path.remove(str(repo))
    assert findings == [], [str(f) for f in findings]
