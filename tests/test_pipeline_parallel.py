"""Pipeline-parallel parity: the compiled scan/ppermute schedule over a
pp mesh must produce the same losses as eager sequential execution of the
SAME PipelineLayer weights (the reference's strategy-vs-single-device
loss-parity pattern, test/collective/fleet/hybrid_parallel_pp_*.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (LayerDesc,
                                                        PipelineLayer,
                                                        SegmentLayers,
                                                        SharedLayerDesc)
from paddle_tpu.models import GPTForCausalLMPipe
from paddle_tpu.models.gpt import GPTConfig

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def gpt_tiny4():
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                     num_heads=4, max_position_embeddings=128)

VOCAB, SEQ, BATCH = 256, 16, 8


def _data(seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, VOCAB, (BATCH, SEQ)).astype("int32")
    labels = rs.randint(0, VOCAB, (BATCH, SEQ)).astype("int32")
    return ids, labels


def _init_fleet(dp, pp, mp=1, vpp=1, accumulate_steps=2,
                micro_batch_size=2):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
        "pp_configs": {"num_virtual_pipeline_stages": vpp}}
    strategy.pipeline_configs = {"accumulate_steps": accumulate_steps,
                                 "micro_batch_size": micro_batch_size}
    fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)
    return fleet.init(is_collective=True, strategy=strategy), strategy


def _eager_losses(model, ids, labels, lr, steps):
    """Sequential (non-SPMD) reference run of the same PipelineLayer."""
    losses = []
    params = [p for p in model.parameters() if p.trainable]
    for _ in range(steps):
        loss = model.compute_loss(paddle.to_tensor(ids),
                                  paddle.to_tensor(labels))
        loss.backward()
        for p in params:
            if p.grad is not None:
                p._value = p._value - lr * p.grad._value
                p.grad = None
                p._grad_node = None
        losses.append(float(loss))
    return losses


def _snapshot(model):
    return [(p, p._value) for p in model.parameters()]


def _restore(snap):
    for p, v in snap:
        p._value = v
        p._grad_node = None
        p.grad = None


def test_segment_layers_uniform():
    assert SegmentLayers.uniform(8, 4) == [0, 2, 4, 6, 8]
    assert SegmentLayers.uniform(10, 4) == [0, 3, 6, 8, 10]


def test_pipeline_layer_structure():
    _init_fleet(dp=2, pp=4)
    cfg = gpt_tiny4()
    model = GPTForCausalLMPipe(cfg)
    assert isinstance(model, PipelineLayer)
    # stacked block params carry the 'pp' leading axis
    sp = model.parameters_in_stacked_blocks
    assert sp and all(p.shape[0] == 4 for p in sp)
    from jax.sharding import PartitionSpec as P

    assert all(tuple(p.dist_attr)[0] == "pp" for p in sp)
    # tied embedding: prologue embedding table is the head weight too
    names = [n for n, _ in model.named_parameters()]
    assert sum("word_embeddings" in n for n in names) == 1


def test_pp_dp_training_parity():
    hcg, strategy = _init_fleet(dp=2, pp=4)
    paddle.seed(11)
    cfg = gpt_tiny4()
    model = GPTForCausalLMPipe(cfg)
    ids, labels = _data(3)
    lr = 0.05

    snap = _snapshot(model)
    golden = _eager_losses(model, ids, labels, lr, steps=3)
    _restore(snap)

    dist_model = fleet.distributed_model(model)
    opt = paddle.optimizer.SGD(learning_rate=lr,
                               parameters=model.parameters())
    opt = fleet.distributed_optimizer(opt)
    losses = [float(dist_model.train_batch(
        [paddle.to_tensor(ids), paddle.to_tensor(labels)], opt))
        for _ in range(3)]
    np.testing.assert_allclose(losses, golden, rtol=2e-4, atol=2e-5)


def test_pp_mp_dp_training_parity():
    hcg, strategy = _init_fleet(dp=2, pp=2, mp=2)
    paddle.seed(13)
    cfg = gpt_tiny4()
    model = GPTForCausalLMPipe(cfg)
    ids, labels = _data(5)
    lr = 0.05

    snap = _snapshot(model)
    golden = _eager_losses(model, ids, labels, lr, steps=2)
    _restore(snap)

    dist_model = fleet.distributed_model(model)
    opt = paddle.optimizer.SGD(learning_rate=lr,
                               parameters=model.parameters())
    opt = fleet.distributed_optimizer(opt)
    losses = [float(dist_model.train_batch(
        [paddle.to_tensor(ids), paddle.to_tensor(labels)], opt))
        for _ in range(2)]
    np.testing.assert_allclose(losses, golden, rtol=2e-4, atol=2e-5)


def test_pp_eval_batch_matches_eager_loss():
    hcg, strategy = _init_fleet(dp=2, pp=4)
    paddle.seed(17)
    cfg = gpt_tiny4()
    model = GPTForCausalLMPipe(cfg)
    ids, labels = _data(7)

    with paddle.no_grad():
        golden = float(model.compute_loss(paddle.to_tensor(ids),
                                          paddle.to_tensor(labels)))

    dist_model = fleet.distributed_model(model)
    opt = paddle.optimizer.SGD(learning_rate=0.0,
                               parameters=model.parameters())
    opt = fleet.distributed_optimizer(opt)
    first = float(dist_model.train_batch(
        [paddle.to_tensor(ids), paddle.to_tensor(labels)], opt))
    np.testing.assert_allclose(first, golden, rtol=2e-4)
    ev = float(dist_model.eval_batch(
        [paddle.to_tensor(ids), paddle.to_tensor(labels)]))
    np.testing.assert_allclose(ev, golden, rtol=2e-4)


def _compiled_temp_bytes(model, M, ids, labels, mesh):
    """XLA temp buffer size of the full loss+backward program at M
    microbatches (the engine's step structure: tape inside shard_map)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed import collective as C
    from paddle_tpu.distributed.engine import _shard_map, bind_params
    from paddle_tpu.tensor import Tensor

    model._num_microbatches = M
    params = [p for p in model.parameters() if p.trainable]
    pvals = tuple(p._value for p in params)
    pspecs = tuple(
        p.dist_attr if getattr(p, "dist_attr", None) is not None else P()
        for p in params)

    def fn(pvals, ids_v, labels_v):
        with C.spmd_region(mesh), bind_params(params, pvals):
            loss = model.compute_loss(
                Tensor(ids_v, stop_gradient=True),
                Tensor(labels_v, stop_gradient=True))
            loss.backward()
            grads = tuple(
                p.grad._value if p.grad is not None
                else jax.numpy.zeros_like(p._value) for p in params)
            for p in params:
                p.grad = None
                p._grad_node = None
        return loss._value, grads

    sm = _shard_map(fn, mesh, (pspecs, P(), P()), (P(), pspecs))
    c = jax.jit(sm).lower(pvals, ids, labels).compile()
    return c.memory_analysis().temp_size_in_bytes


def test_pp_activation_memory_flat_in_microbatches():
    """With tick_checkpoint (default), activation memory must NOT scale
    with microbatch count: only O(microbatch) boundary carries survive
    the forward scan (VERDICT: the 1F1B memory property). M=8 vs M=2
    within 1.35x; without tick_checkpoint the ratio must be visibly
    worse, demonstrating what the checkpoint buys."""
    hcg, _ = _init_fleet(dp=1, pp=2)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                    num_heads=4, max_position_embeddings=128)
    ids = np.random.RandomState(0).randint(0, 256, (8, 16)).astype("int32")
    labels = np.random.RandomState(1).randint(0, 256, (8, 16)).astype(
        "int32")

    paddle.seed(3)
    model = GPTForCausalLMPipe(cfg)
    m2 = _compiled_temp_bytes(model, 2, ids, labels, hcg.mesh)
    m8 = _compiled_temp_bytes(model, 8, ids, labels, hcg.mesh)
    assert m8 <= 1.35 * m2, (m2, m8)

    paddle.seed(3)
    model_nc = GPTForCausalLMPipe(cfg)
    # reach into the private flag: the GPT pipe factory does not expose
    # the PipelineLayer tick_checkpoint kwarg, and this test needs the
    # OFF behavior only to demonstrate the contrast
    model_nc._tick_checkpoint = False
    n2 = _compiled_temp_bytes(model_nc, 2, ids, labels, hcg.mesh)
    n8 = _compiled_temp_bytes(model_nc, 8, ids, labels, hcg.mesh)
    assert n8 / n2 > m8 / max(m2, 1), \
        f"checkpoint off should scale worse: {n2}->{n8} vs {m2}->{m8}"


# ---------------------------------------------------------------------------
# circular interleaved schedule (num_virtual_pipeline_stages > 1)
# ---------------------------------------------------------------------------

def _compiled_loss_and_grads(model, M, ids, labels, mesh):
    """Run the compiled pipelined loss+backward (the engine's step
    structure) and return (loss, {param: grad}) with the engine's
    grad-ownership psums applied (replicated params psum over 'pp')."""
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed import collective as C
    from paddle_tpu.distributed.engine import (_shard_map, bind_params,
                                               param_spec)
    from paddle_tpu.tensor import Tensor

    model._num_microbatches = M
    params = [p for p in model.parameters() if p.trainable]
    pvals = tuple(p._value for p in params)
    pspecs = tuple(param_spec(p) for p in params)

    def _psum_axes(p):
        spec_axes = set()
        for ax in param_spec(p):
            if isinstance(ax, (tuple, list)):
                spec_axes.update(ax)
            elif ax is not None:
                spec_axes.add(ax)
        return tuple(a for a in ("pp",)
                     if a in mesh.axis_names and mesh.shape[a] > 1
                     and a not in spec_axes)

    def fn(pvals, ids_v, labels_v):
        from jax import lax

        with C.spmd_region(mesh), bind_params(params, pvals):
            loss = model.compute_loss(
                Tensor(ids_v, stop_gradient=True),
                Tensor(labels_v, stop_gradient=True))
            loss.backward()
            grads = []
            for p in params:
                g = (p.grad._value if p.grad is not None
                     else jax.numpy.zeros_like(p._value))
                ax = _psum_axes(p)
                if ax:
                    g = lax.psum(g, ax)
                grads.append(g)
            for p in params:
                p.grad = None
                p._grad_node = None
        return loss._value, tuple(grads)

    sm = _shard_map(fn, mesh, (pspecs, P(), P()), (P(), pspecs))
    loss_v, grads = jax.jit(sm)(pvals, ids, labels)
    return float(loss_v), dict(zip([id(p) for p in params],
                                   [np.asarray(g) for g in grads]))


def test_vpp2_loss_and_grad_parity_vs_eager():
    """The circular vpp=2 schedule's compiled loss AND every param grad
    must match sequential eager autodiff of the SAME weights <= 1e-5
    (tied embeddings included: GPTForCausalLMPipe ties the head via
    SharedLayerDesc across stage 0 / last)."""
    hcg, _ = _init_fleet(dp=1, pp=2, vpp=2, accumulate_steps=4,
                         micro_batch_size=2)
    paddle.seed(23)
    cfg = gpt_tiny4()
    model = GPTForCausalLMPipe(cfg)
    ids, labels = _data(9)

    # eager sequential reference on the same model object
    loss_e = model.compute_loss(paddle.to_tensor(ids),
                                paddle.to_tensor(labels))
    loss_e.backward()
    params = [p for p in model.parameters() if p.trainable]
    eager = {id(p): np.asarray(p.grad._value) for p in params
             if p.grad is not None}
    for p in params:
        p.grad = None
        p._grad_node = None

    loss_p, grads = _compiled_loss_and_grads(model, 4, ids, labels,
                                             hcg.mesh)
    np.testing.assert_allclose(loss_p, float(loss_e), rtol=1e-5,
                               atol=1e-6)
    assert eager, "eager reference produced no grads"
    for p in params:
        if id(p) in eager:
            np.testing.assert_allclose(
                grads[id(p)], eager[id(p)], rtol=1e-5, atol=1e-5,
                err_msg=f"grad mismatch for param of shape {p.shape}")


def test_vpp2_vs_vpp1_training_parity_and_compile_stability():
    """vpp=2 must train bit-comparably (<=1e-5) to vpp=1 on the same
    weights/data — and with ZERO steady-state recompiles."""
    cfg = gpt_tiny4()
    ids, labels = _data(13)
    lr = 0.05

    def run(vpp):
        _init_fleet(dp=2, pp=2, vpp=vpp, accumulate_steps=2,
                    micro_batch_size=2)
        paddle.seed(29)
        model = GPTForCausalLMPipe(cfg)
        dist_model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(paddle.optimizer.SGD(
            learning_rate=lr, parameters=model.parameters()))
        losses = [float(dist_model.train_batch(
            [paddle.to_tensor(ids), paddle.to_tensor(labels)], opt))
            for _ in range(3)]
        stats = dist_model._engine.stats
        return losses, stats

    l1, _ = run(1)
    l2, stats2 = run(2)
    np.testing.assert_allclose(l2, l1, rtol=1e-5, atol=1e-6)
    # one (shape, spec) signature -> one compile; steps 2..3 are hits
    assert stats2.compiles == 1 and stats2.cache_hits == 2, \
        (stats2.compiles, stats2.cache_hits)


def test_vpp2_dropout_deterministic_and_distinct_per_step():
    """Dropout under the circular schedule: same seed -> identical
    losses across rebuilds (the (tick, stage, chunk) streams are pure
    functions of the traced step seed), different steps -> different
    masks (losses differ)."""
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                    num_heads=4, max_position_embeddings=128,
                    hidden_dropout=0.2)
    ids, labels = _data(17)

    def run():
        _init_fleet(dp=1, pp=2, vpp=2, accumulate_steps=2,
                    micro_batch_size=4)
        paddle.seed(31)
        model = GPTForCausalLMPipe(cfg)
        model.train()
        dist_model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(paddle.optimizer.SGD(
            learning_rate=0.0, parameters=model.parameters()))
        return [float(dist_model.train_batch(
            [paddle.to_tensor(ids), paddle.to_tensor(labels)], opt))
            for _ in range(2)]

    a = run()
    b = run()
    np.testing.assert_allclose(a, b, rtol=0, atol=0)   # deterministic
    # lr=0 keeps weights fixed, so a step-loss change can only come
    # from the per-step dropout stream
    assert abs(a[0] - a[1]) > 1e-7, a


def test_vpp2_activation_memory_flat_in_microbatches():
    """tick_checkpoint composes with the circular schedule: each tick
    remats only its K-layer chunk, so activation memory stays flat in
    M under vpp=2 as well."""
    hcg, _ = _init_fleet(dp=1, pp=2, vpp=2)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                    num_heads=4, max_position_embeddings=128)
    ids = np.random.RandomState(0).randint(0, 256, (8, 16)).astype("int32")
    labels = np.random.RandomState(1).randint(0, 256, (8, 16)).astype(
        "int32")
    paddle.seed(3)
    model = GPTForCausalLMPipe(cfg)
    m2 = _compiled_temp_bytes(model, 2, ids, labels, hcg.mesh)
    m8 = _compiled_temp_bytes(model, 8, ids, labels, hcg.mesh)
    assert m8 <= 1.35 * m2, (m2, m8)
