"""Pallas TPU kernel numerics (interpret mode on CPU — the reference's
OpTest pattern: kernel output vs a NumPy/XLA reference, fwd + grad)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd
from paddle_tpu.ops.pallas.rms_norm import rms_norm_fused
from paddle_tpu.ops.nn_ops import scaled_dot_product_attention as _sdpa


def _ref_attn(q, k, v, causal):
    return _sdpa.raw(q, k, v, attn_mask=None, dropout_p=0.0,
                     is_causal=causal)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("S", [16, 64])
def test_flash_attention_forward(causal, S):
    rng = np.random.RandomState(0)
    B, H, D = 2, 3, 16
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D).astype("float32"))
               for _ in range(3))
    out = flash_attention_fwd(q, k, v, causal, None, True)
    ref = _ref_attn(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_grads():
    rng = np.random.RandomState(1)
    B, S, H, D = 1, 32, 2, 8
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D).astype("float32"))
               for _ in range(3))

    def f_pallas(q, k, v):
        return jnp.sum(flash_attention_fwd(q, k, v, True, None, True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_ref_attn(q, k, v, True) ** 2)

    gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gp, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=name)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads_multiblock(causal):
    """S=256 → multiple 128-blocks: exercises the dq upper bound and the
    dkv lower bound of the backward kernels across block boundaries."""
    rng = np.random.RandomState(3)
    B, S, H, D = 1, 256, 2, 16
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D).astype("float32"))
               for _ in range(3))
    ct = jnp.asarray(rng.randn(B, S, H, D).astype("float32"))

    def f_pallas(q, k, v):
        return jnp.sum(flash_attention_fwd(q, k, v, causal, None, True)
                       * ct)

    def f_ref(q, k, v):
        return jnp.sum(_ref_attn(q, k, v, causal) * ct)

    gp = jax.grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gp, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-4, err_msg=name)


def test_flash_attention_bf16():
    rng = np.random.RandomState(2)
    B, S, H, D = 2, 32, 2, 16
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D)).astype(jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    out = flash_attention_fwd(q, k, v, True, None, True)
    assert out.dtype == jnp.bfloat16
    ref = _ref_attn(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32), True)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_flash_attention_unsupported_shape_raises():
    q = jnp.zeros((1, 7, 2, 8), jnp.float32)  # S=7: no block divides it
    with pytest.raises(ValueError):
        flash_attention_fwd(q, q, q, True, None, True)


def test_rms_norm_fused():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(6, 5, 32).astype("float32"))
    w = jnp.asarray(rng.rand(32).astype("float32") + 0.5)
    out = rms_norm_fused(x, w, 1e-6, True)
    xf = np.asarray(x)
    ref = xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-6) \
        * np.asarray(w)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    def f(x, w):
        return jnp.sum(rms_norm_fused(x, w, 1e-6, True) ** 2)

    def fr(x, w):
        xf = x.astype(jnp.float32)
        ms = jnp.mean(xf * xf, -1, keepdims=True)
        return jnp.sum((xf * jax.lax.rsqrt(ms + 1e-6) * w) ** 2)

    gx, gw = jax.grad(f, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(fr, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), rtol=1e-4,
                               atol=1e-5)


def test_rms_norm_kernel_vs_dense_parity():
    """The two lowerings the llama dispatch switches between — fused
    kernel (interpret) vs rms_norm_dense — must agree in value AND
    grad, and the Mosaic gate must admit/reject the right shapes."""
    from paddle_tpu.ops.pallas.rms_norm import (rms_norm_dense,
                                                rms_norm_supported)

    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(4, 8, 128).astype("float32"))
    w = jnp.asarray(rng.rand(128).astype("float32") + 0.5)
    assert rms_norm_supported(x.shape)          # 32 rows × H=128 tiles
    assert not rms_norm_supported((3, 5, 96))   # sub-lane H → dense path
    fused = rms_norm_fused(x, w, 1e-6, True)
    dense = rms_norm_dense(x, w, 1e-6)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                               rtol=1e-6, atol=1e-6)

    gk = jax.grad(lambda a, b: jnp.sum(rms_norm_fused(a, b, 1e-6,
                                                      True) ** 2),
                  argnums=(0, 1))(x, w)
    gd = jax.grad(lambda a, b: jnp.sum(rms_norm_dense(a, b, 1e-6) ** 2),
                  argnums=(0, 1))(x, w)
    for a, b in zip(gk, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_llama_rms_norm_module_parity():
    """LlamaRMSNorm (Pallas-dispatch wiring) vs plain nn.RMSNorm on the
    same weights: identical forward and weight grads — the wiring only
    changes the lowering, never the math."""
    from paddle_tpu import nn
    from paddle_tpu.models.llama import LlamaRMSNorm

    rng = np.random.RandomState(9)
    w = rng.rand(32).astype("float32") + 0.5
    xv = rng.randn(2, 6, 32).astype("float32")

    outs, grads = [], []
    for cls in (LlamaRMSNorm, nn.RMSNorm):
        m = cls(32, epsilon=1e-5)
        m.weight.set_value(paddle.to_tensor(w))
        x = paddle.to_tensor(xv)
        out = m(x)
        (out ** 2).sum().backward()
        outs.append(np.asarray(out._value))
        grads.append(np.asarray(m.weight.grad._value))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(grads[0], grads[1], rtol=1e-5, atol=1e-6)


def test_incubate_fused_functional():
    """Reference-name fused surface: rms_norm/rope/bias_act/swiglu."""
    import paddle_tpu.incubate.nn.functional as FF

    rng = np.random.RandomState(4)
    x = paddle.to_tensor(rng.randn(2, 6, 16).astype("float32"))
    w = paddle.to_tensor(rng.rand(16).astype("float32"))
    res = paddle.to_tensor(rng.randn(2, 6, 16).astype("float32"))

    out = FF.fused_rms_norm(x, w)
    assert out.shape == [2, 6, 16]
    out, res_out = FF.fused_rms_norm(x, w, residual=res)
    np.testing.assert_allclose(np.asarray(res_out._value),
                               np.asarray((x + res)._value), rtol=1e-6)

    ln_b = paddle.to_tensor(np.zeros(16, "float32"))
    out2 = FF.fused_layer_norm(x, w, ln_b)
    assert out2.shape == [2, 6, 16]

    B, S, H, D = 2, 8, 2, 8
    q = paddle.to_tensor(rng.randn(B, S, H, D).astype("float32"))
    k = paddle.to_tensor(rng.randn(B, S, H, D).astype("float32"))
    inv = 1.0 / 10000 ** (np.arange(0, D, 2) / D)
    t = np.arange(S)[:, None] * inv[None, :]
    cos = paddle.to_tensor(np.cos(np.concatenate([t, t], -1))
                           .astype("float32"))
    sin = paddle.to_tensor(np.sin(np.concatenate([t, t], -1))
                           .astype("float32"))
    qr, kr, _ = FF.fused_rotary_position_embedding(q, k, sin=sin, cos=cos)
    assert qr.shape == [B, S, H, D] and kr.shape == [B, S, H, D]
    # rope preserves norms
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qr._value), axis=-1),
        np.linalg.norm(np.asarray(q._value), axis=-1), rtol=1e-4)

    y = FF.fused_bias_act(x, bias=paddle.to_tensor(
        np.zeros(16, "float32")), act_method="gelu")
    assert y.shape == [2, 6, 16]

    sw = FF.swiglu(x)
    assert sw.shape == [2, 6, 8]


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_rectangular(causal):
    """seq_q != seq_kv (q rows are the LAST Sq rows under causal)."""
    rng = np.random.RandomState(2)
    B, H, D = 2, 2, 16
    q = jnp.asarray(rng.randn(B, 32, H, D).astype("float32"))
    k = jnp.asarray(rng.randn(B, 128, H, D).astype("float32"))
    v = jnp.asarray(rng.randn(B, 128, H, D).astype("float32"))
    out = flash_attention_fwd(q, k, v, causal, None, True)
    # dense reference with explicit rectangular causal mask
    qf, kf, vf = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    s = jnp.einsum("bhsd,bhtd->bhst", qf, kf) / np.sqrt(D)
    if causal:
        keep = (96 + jnp.arange(32)[:, None]) >= jnp.arange(128)[None, :]
        s = jnp.where(keep[None, None], s, -1e30)
    ref = jnp.swapaxes(
        jnp.einsum("bhst,bhtd->bhsd", jax.nn.softmax(s, -1), vf), 1, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_segment_ids():
    """Packed varlen: tokens only attend within their own segment."""
    rng = np.random.RandomState(3)
    B, S, H, D = 2, 64, 2, 16
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D).astype("float32"))
               for _ in range(3))
    seg = np.zeros((B, S), np.int32)
    seg[:, 20:45] = 1
    seg[:, 45:] = 2
    seg = jnp.asarray(seg)
    out = flash_attention_fwd(q, k, v, True, None, True, seg, seg)
    qf, kf, vf = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    s = jnp.einsum("bhsd,bhtd->bhst", qf, kf) / np.sqrt(D)
    keep = (jnp.arange(S)[:, None] >= jnp.arange(S)[None, :])[None, None]
    keep = keep & (seg[:, None, :, None] == seg[:, None, None, :])
    s = jnp.where(keep, s, -1e30)
    ref = jnp.swapaxes(
        jnp.einsum("bhst,bhtd->bhsd", jax.nn.softmax(s, -1), vf), 1, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # grads flow through the masked kernel
    g = jax.grad(lambda q: jnp.sum(
        flash_attention_fwd(q, k, v, True, None, True, seg, seg) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize("kv_heads", [8, 2])   # MHA and GQA
@pytest.mark.parametrize("offset", [0, 37, 200])
def test_decode_attention_kernel(kv_heads, offset):
    """Streaming cache-KV decode kernel vs the dense cache attention."""
    from paddle_tpu.models.llama import _cache_attention_dense
    from paddle_tpu.ops.pallas.decode_attention import decode_attention

    rng = np.random.RandomState(4)
    B, Sq, H, D, M = 2, 1, 8, 32, 256
    q = jnp.asarray(rng.randn(B, Sq, H, D).astype("float32"))
    kc = jnp.asarray(rng.randn(B, kv_heads, M, D).astype("float32"))
    vc = jnp.asarray(rng.randn(B, kv_heads, M, D).astype("float32"))
    out = decode_attention(q, kc, vc, offset, interpret=True)
    ref = _cache_attention_dense(q, kc, vc, offset, Sq)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_decode_attention_chunked_prefill():
    """Sq>1 chunk against a partially-filled cache (chunked prefill) with
    a traced offset under jit."""
    from paddle_tpu.models.llama import _cache_attention_dense
    from paddle_tpu.ops.pallas.decode_attention import decode_attention

    rng = np.random.RandomState(5)
    B, Sq, H, D, M = 1, 16, 4, 32, 128
    q = jnp.asarray(rng.randn(B, Sq, H, D).astype("float32"))
    kc = jnp.asarray(rng.randn(B, H, M, D).astype("float32"))
    vc = jnp.asarray(rng.randn(B, H, M, D).astype("float32"))
    f = jax.jit(lambda q, kc, vc, off: decode_attention(
        q, kc, vc, off, interpret=True))
    for off in (0, 50, M - Sq):
        out = f(q, kc, vc, off)
        ref = _cache_attention_dense(q, kc, vc, off, Sq)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_flash_attn_unpadded_varlen():
    """Packed sequences attend only within their own boundaries."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(6)
    lens = [24, 40, 64]
    total, H, D = sum(lens), 2, 16
    q = rng.randn(total, H, D).astype("float32")
    k = rng.randn(total, H, D).astype("float32")
    v = rng.randn(total, H, D).astype("float32")
    cu = np.cumsum([0] + lens).astype("int32")
    out, _ = F.flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu), paddle.to_tensor(cu), causal=True)
    out = np.asarray(out._value)
    for i in range(len(lens)):
        a, b = cu[i], cu[i + 1]
        ref = _sdpa.raw(jnp.asarray(q[None, a:b]), jnp.asarray(k[None, a:b]),
                        jnp.asarray(v[None, a:b]), attn_mask=None,
                        dropout_p=0.0, is_causal=True)[0]
        np.testing.assert_allclose(out[a:b], np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_flash_attn_unpadded_causal_unequal_packs():
    """causal varlen with cu_seqlens_q != cu_seqlens_k: each sequence
    gets its OWN bottom-right-aligned frontier (review finding: a global
    Tk-Tq shift misaligned every sequence but the last)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(7)
    lens_q, lens_k = [2, 3], [4, 5]
    Tq, Tk, H, D = sum(lens_q), sum(lens_k), 2, 16
    q = rng.randn(Tq, H, D).astype("float32")
    k = rng.randn(Tk, H, D).astype("float32")
    v = rng.randn(Tk, H, D).astype("float32")
    cq = np.cumsum([0] + lens_q).astype("int32")
    ck = np.cumsum([0] + lens_k).astype("int32")
    out, _ = F.flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cq), paddle.to_tensor(ck), causal=True)
    out = np.asarray(out._value)
    for i in range(len(lens_q)):
        qa, qb = cq[i], cq[i + 1]
        ka, kb = ck[i], ck[i + 1]
        ref = _sdpa.raw(jnp.asarray(q[None, qa:qb]),
                        jnp.asarray(k[None, ka:kb]),
                        jnp.asarray(v[None, ka:kb]),
                        attn_mask=None, dropout_p=0.0, is_causal=True)[0]
        np.testing.assert_allclose(out[qa:qb], np.asarray(ref),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"sequence {i}")
