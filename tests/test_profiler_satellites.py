"""Profiler correctness satellites (tier-1; the launcher/subprocess
profiler tests stay in the slow suite).

Under test (paddle_tpu/profiler):
- an UNSTARTED profiler must not leak dispatch events into the global
  list (_op_record honors the same `_active` gate as RecordEvent.end)
- Profiler.step(num_samples=...) drives an ips (samples/sec) line in
  summary() like the reference paddle.profiler
- the chrome exporter records the EMITTING thread id (worker threads /
  watchdog monitor separate into lanes) + thread_name metadata
- make_scheduler edges: skip_first, repeat exhaustion, and
  RECORD_AND_RETURN exactly on the last record step of each span
- start/stop re-entrancy: nested profilers keep `_active` balanced,
  the inner stop neither clears the outer's events nor removes the
  dispatch hook
"""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.profiler as profiler
from paddle_tpu.core import dispatch as _dispatch


@pytest.fixture(autouse=True)
def _clean_profiler_state():
    """Each test starts with no active profiler and an empty event
    list (the module state is global by design)."""
    with profiler._events_lock:
        profiler._events.clear()
    profiler._active = 0
    _dispatch._profile_hook = None
    yield
    profiler._active = 0
    _dispatch._profile_hook = None


def _mm():
    x = paddle.to_tensor(np.ones((4, 4), "float32"))
    return paddle.matmul(x, x)


# ---------------------------------------------------------------------------
# satellite: _op_record must not record when no profiler is active
# ---------------------------------------------------------------------------
class TestInactiveRecording:
    def test_op_record_inactive_no_leak(self):
        # a stale hook (e.g. left by an unbalanced stop) must not grow
        # the global event list while _active == 0
        _dispatch._profile_hook = profiler._op_record
        _mm()
        assert profiler._events == []

    def test_record_event_inactive_no_leak(self):
        with profiler.RecordEvent("orphan"):
            pass
        assert profiler._events == []

    def test_stop_mid_op_drops_event(self):
        # _active re-checked at append time, mirroring RecordEvent.end
        with profiler._op_record("op"):
            pass                       # _active == 0 the whole time
        assert profiler._events == []


# ---------------------------------------------------------------------------
# satellite: step(num_samples) -> ips in summary
# ---------------------------------------------------------------------------
class TestThroughput:
    def test_summary_reports_ips(self):
        with profiler.Profiler(timer_only=True) as p:
            for _ in range(3):
                _mm()
                time.sleep(0.002)
                p.step(num_samples=16)
        out = p.summary()
        assert "ips" in out and "48 samples" in out
        tot_t = sum(d for d, _ in p._samples)
        ips = 48 / tot_t
        assert f"{ips:.2f}" in out

    def test_no_samples_no_ips_line(self):
        with profiler.Profiler(timer_only=True) as p:
            _mm()
            p.step()
        assert "ips" not in p.summary()

    def test_interval_accounting(self):
        p = profiler.Profiler(timer_only=True)
        p.start()
        time.sleep(0.005)
        p.step(num_samples=10)
        p.stop()
        (dur, n), = p._samples
        assert n == 10 and dur >= 0.004


# ---------------------------------------------------------------------------
# satellite: chrome exporter thread lanes
# ---------------------------------------------------------------------------
class TestChromeThreadLanes:
    def test_events_carry_real_tids(self, tmp_path):
        with profiler.Profiler(timer_only=True) as p:
            def worker():
                with profiler.RecordEvent("worker_block"):
                    _mm()

            t = threading.Thread(target=worker, name="svc-worker-0")
            t.start()
            t.join()
            with profiler.RecordEvent("main_block"):
                _mm()
        path = str(tmp_path / "trace.json")
        p._export_chrome(path)
        data = json.load(open(path))
        evs = [e for e in data["traceEvents"] if e["ph"] == "X"]
        by_name = {e["name"]: e for e in evs}
        assert by_name["worker_block"]["tid"] \
            != by_name["main_block"]["tid"]
        assert all(e["tid"] != 0 for e in evs)
        lanes = {e["args"]["name"] for e in data["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert {"svc-worker-0", "MainThread"} <= lanes

    def test_ops_attributed_to_dispatch_thread(self, tmp_path):
        with profiler.Profiler(timer_only=True) as p:
            tids = []

            def worker():
                tids.append(threading.get_ident())
                _mm()

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        path = str(tmp_path / "trace.json")
        p._export_chrome(path)
        data = json.load(open(path))
        op = [e for e in data["traceEvents"]
              if e["ph"] == "X" and e["name"] == "matmul"]
        assert op and op[0]["tid"] == tids[0]


# ---------------------------------------------------------------------------
# satellite: make_scheduler state-transition edges
# ---------------------------------------------------------------------------
class TestSchedulerEdges:
    def test_skip_first_window_closed(self):
        sched = profiler.make_scheduler(closed=0, ready=0, record=2,
                                        skip_first=3)
        S = profiler.ProfilerState
        assert [sched(i) for i in range(3)] == [S.CLOSED] * 3
        assert sched(3) == S.RECORD
        assert sched(4) == S.RECORD_AND_RETURN

    def test_record_and_return_on_last_record_step(self):
        sched = profiler.make_scheduler(closed=1, ready=1, record=3)
        S = profiler.ProfilerState
        # period = 5: steps 2,3 RECORD; 4 (last of the span) RETURNs
        assert [sched(i) for i in range(5)] == [
            S.CLOSED, S.READY, S.RECORD, S.RECORD, S.RECORD_AND_RETURN]
        # repeat=0 cycles forever
        assert sched(9) == S.RECORD_AND_RETURN

    def test_repeat_exhaustion_closes(self):
        sched = profiler.make_scheduler(closed=1, ready=0, record=1,
                                        repeat=2)
        S = profiler.ProfilerState
        assert [sched(i) for i in range(6)] == [
            S.CLOSED, S.RECORD_AND_RETURN,
            S.CLOSED, S.RECORD_AND_RETURN,
            S.CLOSED, S.CLOSED]        # past repeat*period: closed

    def test_record_one_is_immediately_return(self):
        sched = profiler.make_scheduler(closed=0, ready=0, record=1)
        assert sched(0) == profiler.ProfilerState.RECORD_AND_RETURN


# ---------------------------------------------------------------------------
# satellite: start/stop re-entrancy
# ---------------------------------------------------------------------------
class TestReentrancy:
    def test_nested_profilers_balance_active(self):
        outer = profiler.Profiler(timer_only=True)
        inner = profiler.Profiler(timer_only=True)
        outer.start()
        assert profiler._active == 1
        inner.start()
        assert profiler._active == 2
        inner.stop()
        assert profiler._active == 1
        # the hook survives the inner stop: ops still recorded
        _mm()
        assert any(e[0] == "matmul" for e in profiler._events)
        outer.stop()
        assert profiler._active == 0
        assert _dispatch._profile_hook is None

    def test_inner_start_keeps_outer_events(self):
        outer = profiler.Profiler(timer_only=True)
        outer.start()
        with profiler.RecordEvent("before_inner"):
            pass
        with profiler.Profiler(timer_only=True):
            pass
        assert any(e[0] == "before_inner" for e in profiler._events)
        outer.stop()

    def test_unbalanced_stop_clamps_at_zero(self):
        p = profiler.Profiler(timer_only=True)
        p.start()
        p.stop()
        p.stop()                        # extra stop must not go negative
        assert profiler._active == 0
        q = profiler.Profiler(timer_only=True)
        q.start()                       # and a fresh start still works
        _mm()
        assert any(e[0] == "matmul" for e in profiler._events)
        q.stop()
