"""Weight-only quantized serving (reference: python/paddle/nn/quant/
quantized_linear.py — weight_quantize/weight_only_linear/
llm_int8_linear over the fusion CUDA kernels)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn

import pytest

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def _setup():
    rng = np.random.RandomState(0)
    w = paddle.to_tensor(rng.randn(64, 32).astype("float32") * 0.1)
    x = paddle.to_tensor(rng.randn(4, 64).astype("float32"))
    b = paddle.to_tensor(rng.randn(32).astype("float32"))
    ref = np.asarray(x._value) @ np.asarray(w._value) + np.asarray(b._value)
    return w, x, b, ref


def test_weight_quantize_roundtrip_int8():
    w, *_ = _setup()
    q, s = nn.quant.weight_quantize(w, "weight_only_int8")
    assert q.shape == [32, 64] and "int8" in str(q.dtype)
    back = nn.quant.weight_dequantize(q, s, out_dtype="float32")
    err = np.abs(np.asarray(back._value) - np.asarray(w._value)).max()
    assert err <= float(np.asarray(s._value).max()) / 2 + 1e-6


def test_weight_quantize_roundtrip_int4():
    w, *_ = _setup()
    q, s = nn.quant.weight_quantize(w, "weight_only_int4")
    assert q.shape == [32, 32]  # two nibbles per byte
    back = nn.quant.weight_dequantize(q, s, "weight_only_int4", "float32")
    err = np.abs(np.asarray(back._value) - np.asarray(w._value)).max()
    assert err <= float(np.asarray(s._value).max()) / 2 + 1e-6


def test_weight_only_linear_parity():
    w, x, b, ref = _setup()
    q, s = nn.quant.weight_quantize(w, "weight_only_int8")
    y = np.asarray(nn.quant.weight_only_linear(x, q, b, s, "int8")._value)
    assert np.abs(y - ref).max() / np.abs(ref).max() < 0.02
    q4, s4 = nn.quant.weight_quantize(w, "weight_only_int4")
    y4 = np.asarray(nn.quant.weight_only_linear(x, q4, b, s4, "int4")._value)
    assert np.abs(y4 - ref).max() / np.abs(ref).max() < 0.3


def test_llm_int8_linear_parity():
    w, x, b, ref = _setup()
    q, s = nn.quant.weight_quantize(w, "llm.int8")
    y = np.asarray(nn.quant.llm_int8_linear(x, q, b, s, 2.0)._value)
    assert np.abs(y - ref).max() / np.abs(ref).max() < 0.05


def test_weight_only_layer_and_swap():
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(64, 32), nn.ReLU(), nn.Linear(32, 16))
    x = paddle.to_tensor(np.random.RandomState(1).randn(2, 64)
                         .astype("float32"))
    ref = np.asarray(m(x)._value)
    nn.quant.quantize_for_serving(m)
    assert isinstance(m[0], nn.quant.WeightOnlyLinear)
    assert isinstance(m[2], nn.quant.WeightOnlyLinear)
    out = np.asarray(m(x)._value)
    assert np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9) < 0.05
    # quantized weights are registered parameters (bindable buffers)
    names = [n for n, _ in m.named_parameters()]
    assert any("weight_quant" in n for n in names)


def test_predictor_weight_only_greedy_parity():
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    rng = np.random.RandomState(3)
    prompt = paddle.to_tensor(rng.randint(0, 256, (1, 24)))
    paddle.seed(0)
    pred_fp = create_predictor(Config().set_model(
        LlamaForCausalLM(llama_tiny())))
    out_fp = np.asarray(pred_fp.generate(prompt, max_new_tokens=8)._value)
    paddle.seed(0)
    pred_q = create_predictor(Config().set_model(
        LlamaForCausalLM(llama_tiny())).enable_weight_only())
    out_q = np.asarray(pred_q.generate(prompt, max_new_tokens=8)._value)
    assert (out_fp == out_q).mean() > 0.9


def test_enable_weight_only_validates_algo():
    from paddle_tpu.inference import Config

    import pytest
    with pytest.raises(ValueError, match="weight_only_int8"):
        Config().enable_weight_only("llm.int8")


def test_int4_odd_indim_warns():
    import warnings

    m = nn.Sequential(nn.Linear(7, 4))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        nn.quant.quantize_for_serving(m, "weight_only_int4")
    assert any("odd in_features" in str(x.message) for x in w)
    assert isinstance(m[0], nn.Linear)  # kept fp
