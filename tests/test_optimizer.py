"""Optimizer correctness + convergence tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _fit(opt_cls, steps=60, **kw):
    np.random.seed(0)
    paddle.seed(0)
    w_true = np.array([[2.0], [-3.0]], np.float32)
    x = np.random.randn(64, 2).astype(np.float32)
    y = x @ w_true
    model = nn.Linear(2, 1)
    opt = opt_cls(parameters=model.parameters(), **kw)
    loss_val = None
    for _ in range(steps):
        pred = model(paddle.to_tensor(x))
        loss = nn.functional.mse_loss(pred, paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        loss_val = float(loss.numpy())
    return loss_val


class TestConvergence:
    def test_sgd(self):
        assert _fit(optimizer.SGD, learning_rate=0.1) < 1e-2

    def test_momentum(self):
        # 60 steps lands at 0.0112 — a hair ABOVE the 1e-2 bar, so the
        # test's outcome used to hinge on unrelated cross-module state;
        # 80 steps converges to ~1e-3, deterministic in any test order
        assert _fit(optimizer.Momentum, steps=80, learning_rate=0.05) < 1e-2

    def test_adam(self):
        assert _fit(optimizer.Adam, steps=150, learning_rate=0.1) < 1e-2

    def test_adamw(self):
        assert _fit(optimizer.AdamW, steps=150, learning_rate=0.1,
                    weight_decay=0.001) < 1e-2


class TestSemantics:
    def test_adam_matches_reference_formula(self):
        p0 = np.array([1.0], np.float32)
        g = np.array([0.5], np.float32)
        p = paddle.Parameter(paddle.to_tensor(p0)._value)
        p.grad = paddle.to_tensor(g)
        opt = optimizer.Adam(learning_rate=0.1, parameters=[p])
        opt.step()
        m = 0.1 * g
        v = 0.001 * g * g
        mhat = m / 0.1
        vhat = v / 0.001
        ref = p0 - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
        np.testing.assert_allclose(p.numpy(), ref, rtol=1e-5)

    def test_grad_clip_global_norm(self):
        p = paddle.Parameter(paddle.to_tensor(np.zeros(4, np.float32))._value)
        p.grad = paddle.to_tensor(np.full(4, 10.0, np.float32))
        opt = optimizer.SGD(learning_rate=1.0, parameters=[p],
                            grad_clip=nn.ClipGradByGlobalNorm(1.0))
        opt.step()
        # grad norm 20 -> clipped to 1 -> each component 0.5
        np.testing.assert_allclose(p.numpy(), -np.full(4, 0.5), rtol=1e-5)

    def test_grad_clip_global_norm_includes_sparse(self):
        """SelectedRows grads join the global norm and scale by the
        same coefficient as the dense grads (reference:
        ClipGradByGlobalNorm merges + clips sparse grads)."""
        from paddle_tpu.framework.selected_rows import SelectedRows

        pd = paddle.Parameter(
            paddle.to_tensor(np.zeros(4, np.float32))._value)
        pd.grad = paddle.to_tensor(np.full(4, 3.0, np.float32))
        pe = paddle.Parameter(
            paddle.to_tensor(np.zeros((8, 4), np.float32))._value)
        # duplicate row ids: merged (accumulated) BEFORE the norm
        pe.grad = SelectedRows([1, 3, 1], np.full((3, 4), 2.0,
                                                  np.float32), 8)
        opt = optimizer.SGD(learning_rate=1.0, parameters=[pd, pe],
                            grad_clip=nn.ClipGradByGlobalNorm(1.0))
        opt.step()
        # merged sparse: row1=4.0, row3=2.0 ->
        # gn = sqrt(4*9 + 4*16 + 4*4) = sqrt(116)
        gn = np.sqrt(116.0)
        np.testing.assert_allclose(pd.numpy(), -np.full(4, 3.0 / gn),
                                   rtol=1e-5)
        ref = np.zeros((8, 4), np.float32)
        ref[1] = -4.0 / gn
        ref[3] = -2.0 / gn
        np.testing.assert_allclose(pe.numpy(), ref, rtol=1e-5)
        # below the threshold nothing scales
        pd.grad = paddle.to_tensor(np.full(4, 3.0, np.float32))
        pe.grad = SelectedRows([2], np.full((1, 4), 2.0, np.float32), 8)
        opt2 = optimizer.SGD(learning_rate=1.0, parameters=[pd, pe],
                             grad_clip=nn.ClipGradByGlobalNorm(100.0))
        before = pe.numpy().copy()
        opt2.step()
        np.testing.assert_allclose(pe.numpy()[2], before[2] - 2.0,
                                   rtol=1e-5)

    def test_lr_scheduler(self):
        sched = optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        p = paddle.Parameter(paddle.to_tensor(np.zeros(1, np.float32))._value)
        opt = optimizer.SGD(learning_rate=sched, parameters=[p])
        assert abs(opt.get_lr() - 0.1) < 1e-9
        sched.step(); sched.step()
        assert abs(opt.get_lr() - 0.05) < 1e-9

    def test_state_dict_roundtrip(self):
        p = paddle.Parameter(paddle.to_tensor(np.ones(3, np.float32))._value)
        p.name = "p"
        p.grad = paddle.to_tensor(np.ones(3, np.float32))
        opt = optimizer.Adam(learning_rate=0.1, parameters=[p])
        opt.step()
        sd = opt.state_dict()
        opt2 = optimizer.Adam(learning_rate=0.1, parameters=[p])
        opt2.set_state_dict(sd)
        assert opt2._step_count == 1
        np.testing.assert_allclose(
            np.asarray(opt2._states[id(p)]["moment1"]),
            np.asarray(opt._states[id(p)]["moment1"]))

    def test_clear_grad(self):
        p = paddle.Parameter(paddle.to_tensor(np.ones(1, np.float32))._value)
        p.grad = paddle.to_tensor(np.ones(1, np.float32))
        opt = optimizer.SGD(learning_rate=0.1, parameters=[p])
        opt.clear_grad()
        assert p.grad is None


class TestRegularizerModes:
    def test_l2_decay_object(self):
        v = np.array([2.0, -2.0], np.float32)
        p = paddle.Parameter(paddle.to_tensor(v)._value)
        p.grad = paddle.to_tensor(np.zeros(2, np.float32))
        opt = optimizer.SGD(learning_rate=0.1, parameters=[p],
                            weight_decay=paddle.regularizer.L2Decay(0.5))
        opt.step()
        # g = wd * p -> p_new = p - lr*wd*p = p * (1 - 0.05)
        np.testing.assert_allclose(np.asarray(p._value), v * 0.95,
                                   rtol=1e-6)

    def test_l1_decay_is_subgradient(self):
        v = np.array([2.0, -2.0], np.float32)
        p = paddle.Parameter(paddle.to_tensor(v)._value)
        p.grad = paddle.to_tensor(np.zeros(2, np.float32))
        opt = optimizer.SGD(learning_rate=0.1, parameters=[p],
                            weight_decay=paddle.regularizer.L1Decay(0.5))
        opt.step()
        # g = wd * sign(p) -> p_new = p - lr*wd*sign(p) = |p| - 0.05
        np.testing.assert_allclose(np.asarray(p._value),
                                   np.array([1.95, -1.95], np.float32),
                                   rtol=1e-6)
