"""End-to-end slice: ResNet training decreases loss; to_static compiled
step matches eager (SURVEY.md §7 step 3 milestone)."""
import pytest
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.vision.models import resnet18

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


def _data(n=8):
    rng = np.random.RandomState(0)
    x = rng.rand(n, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, (n,))
    return x, y


class TestResNetE2E:
    def test_forward_shape(self):
        m = resnet18(num_classes=10)
        m.eval()
        out = m(paddle.to_tensor(_data(2)[0][:2]))
        assert out.shape == [2, 10]

    def test_overfit_small_batch(self):
        paddle.seed(0)
        m = resnet18(num_classes=10)
        m.train()
        opt = optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
        x, y = _data(4)
        xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
        losses = []
        for _ in range(4):
            loss = nn.functional.cross_entropy(m(xt), yt)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


class TestToStatic:
    def test_traced_step_matches_eager(self):
        paddle.seed(0)
        x, y = _data(4)
        xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)

        def build():
            paddle.seed(1)
            m = nn.Sequential(nn.Flatten(0 if False else 1),
                              nn.Linear(3 * 32 * 32, 32), nn.ReLU(),
                              nn.Linear(32, 10))
            opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
            return m, opt

        # eager
        m1, o1 = build()
        for _ in range(3):
            loss = nn.functional.cross_entropy(m1(xt), yt)
            loss.backward()
            o1.step()
            o1.clear_grad()
        # compiled
        m2, o2 = build()

        def step(xb, yb):
            loss = nn.functional.cross_entropy(m2(xb), yb)
            loss.backward()
            o2.step()
            o2.clear_grad()
            return loss

        compiled = paddle.jit.to_static(step, trackables=[m2, o2])
        for _ in range(3):
            loss2 = compiled(xt, yt)
        np.testing.assert_allclose(m1._sub_layers["1"].weight.numpy(),
                                   m2._sub_layers["1"].weight.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_traced_inference(self):
        m = nn.Linear(4, 2)
        m.eval()
        x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
        eager = m(x).numpy()
        compiled = paddle.jit.to_static(m)
        out = m(x)
        np.testing.assert_allclose(out.numpy(), eager, rtol=1e-5)
