"""Static declare-then-run mode (reference: python/paddle/static/ over
the C++ interpreter; here op recording at the dispatch chokepoint +
eager/jit replay — see paddle_tpu/static/__init__.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static


def _build_regression():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 13], "float32")
        y = static.data("y", [None, 1], "float32")
        lin = nn.Linear(13, 1)
        pred = lin(x)
        loss = ((pred - y) ** 2).mean()
    return main, startup, x, y, pred, loss, lin


def test_recording():
    main, _, x, y, pred, loss, _ = _build_regression()
    assert isinstance(pred, static.Variable)
    assert isinstance(loss, static.Variable)
    names = [n.opdef.name for n in main._nodes]
    assert "linear" in names or "matmul" in names
    assert "mean" in names
    assert loss.shape == []  # scalar metadata from eval_shape
    assert "x" in main._feeds and "y" in main._feeds


def test_variable_has_no_value():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
    with pytest.raises(RuntimeError, match="no value at build time"):
        x.numpy()


def test_executor_train_loop():
    main, startup, x, y, pred, loss, lin = _build_regression()
    with static.program_guard(main, startup):
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=lin.parameters())
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(startup)  # documented no-op: params init eagerly
    rng = np.random.RandomState(0)
    X = rng.rand(32, 13).astype("float32")
    Y = X @ rng.rand(13, 1).astype("float32")
    losses = [float(exe.run(main, feed={"x": X, "y": Y},
                            fetch_list=[loss])[0])
              for _ in range(60)]
    assert losses[-1] < losses[0] * 0.05


def test_fetch_intermediate_and_feed_validation():
    main, _, x, y, pred, loss, _ = _build_regression()
    exe = static.Executor()
    X = np.random.rand(4, 13).astype("float32")
    Y = np.random.rand(4, 1).astype("float32")
    p, l = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[pred, loss])
    assert p.shape == (4, 1) and l.shape == ()
    with pytest.raises(Exception, match="missing feed"):
        exe.run(main, feed={"x": X}, fetch_list=[loss])


def test_clone_for_test_drops_objective():
    main, startup, x, y, pred, loss, lin = _build_regression()
    with static.program_guard(main, startup):
        paddle.optimizer.SGD(learning_rate=0.1,
                             parameters=lin.parameters()).minimize(loss)
    infer = main.clone(for_test=True)
    assert infer._train_objective is None
    assert main._train_objective is not None


def test_compiled_program_matches_eager_replay():
    main, _, x, y, pred, loss, _ = _build_regression()
    exe = static.Executor()
    X = np.random.RandomState(1).rand(8, 13).astype("float32")
    Y = np.random.RandomState(2).rand(8, 1).astype("float32")
    cp = static.CompiledProgram(main)
    out1, = cp.run({"x": X, "y": Y}, [pred])
    out2, = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[pred])
    np.testing.assert_allclose(out1, out2, atol=1e-6)


def test_compiled_program_rejects_train():
    main, startup, x, y, pred, loss, lin = _build_regression()
    with static.program_guard(main, startup):
        paddle.optimizer.SGD(learning_rate=0.1,
                             parameters=lin.parameters()).minimize(loss)
    with pytest.raises(Exception, match="inference"):
        static.CompiledProgram(main)


def test_program_isolation():
    p1, p2 = static.Program(), static.Program()
    with static.program_guard(p1):
        a = static.data("a", [2], "float32")
        _ = a + 1.0
    with static.program_guard(p2):
        b = static.data("b", [2], "float32")
        _ = b * 2.0
    assert len(p1._nodes) == 1 and len(p2._nodes) == 1
    with pytest.raises(Exception, match="different Programs"):
        _ = a + b


def test_enable_disable_static_mode():
    assert paddle.in_dynamic_mode()
    paddle.enable_static()
    try:
        assert not paddle.in_dynamic_mode()
    finally:
        paddle.disable_static()
    assert paddle.in_dynamic_mode()


def test_eager_minimize_still_works():
    paddle.seed(0)
    lin = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).rand(8, 4)
                         .astype("float32"))
    y = paddle.to_tensor(np.zeros((8, 1), "float32"))
    l0 = None
    for _ in range(5):
        loss = ((lin(x) - y) ** 2).mean()
        opt.minimize(loss)
        if l0 is None:
            l0 = float(loss)
    assert float(loss) < l0


def test_compiled_program_sees_weight_updates():
    main, _, x, y, pred, loss, lin = _build_regression()
    X = np.random.RandomState(3).rand(4, 13).astype("float32")
    Y = np.zeros((4, 1), "float32")
    cp = static.CompiledProgram(main)
    out1, = cp.run({"x": X, "y": Y}, [pred])
    # mutate the weights after compilation; the cached executable must
    # pick up the new values (params are traced args, not constants)
    lin.weight._value = lin.weight._value * 0.0
    out2, = cp.run({"x": X, "y": Y}, [pred])
    assert np.abs(out1).max() > 0
    np.testing.assert_allclose(out2, np.tile(
        np.asarray(lin.bias._value), (4, 1)), atol=1e-6)


def test_executor_accepts_compiled_program():
    main, _, x, y, pred, loss, _ = _build_regression()
    exe = static.Executor()
    X = np.random.rand(4, 13).astype("float32")
    Y = np.random.rand(4, 1).astype("float32")
    cp = static.CompiledProgram(main)
    out, = exe.run(cp, feed={"x": X, "y": Y}, fetch_list=[pred])
    assert out.shape == (4, 1)


def test_clone_then_guard_records_into_clone():
    main, _, x, y, pred, loss, _ = _build_regression()
    n_main = len(main._nodes)
    infer = main.clone(for_test=True)
    with static.program_guard(infer):
        doubled = pred * 2.0
    assert len(main._nodes) == n_main          # original untouched
    assert len(infer._nodes) == n_main + 1
    exe = static.Executor()
    X = np.random.RandomState(4).rand(4, 13).astype("float32")
    Y = np.zeros((4, 1), "float32")
    p, d = exe.run(infer, feed={"x": X, "y": Y},
                   fetch_list=[pred, doubled])
    np.testing.assert_allclose(d, p * 2.0, rtol=1e-6)
