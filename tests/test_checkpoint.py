"""Checkpoint tests: paddle.save/load roundtrip and the sharded
distributed checkpoint with reshard-on-load (the reference's
save_state_dict/load_state_dict contract)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine


def _mlp(d=16, h=32):
    return paddle.nn.Sequential(paddle.nn.Linear(d, h), paddle.nn.ReLU(),
                                paddle.nn.Linear(h, d))


def test_save_load_roundtrip(tmp_path):
    m = _mlp()
    opt = paddle.optimizer.Adam(parameters=m.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 16)
                         .astype("float32"))
    loss = paddle.mean(m(x) ** 2)
    loss.backward()
    opt.step()

    p = str(tmp_path / "ckpt" / "model.pdparams")
    paddle.save(m.state_dict(), p)
    paddle.save(opt.state_dict(), str(tmp_path / "ckpt" / "opt.pdopt"))

    m2 = _mlp()
    m2.set_state_dict(paddle.load(p))
    for (n, a), (_, b) in zip(m.named_parameters(), m2.named_parameters()):
        np.testing.assert_array_equal(np.asarray(a._value),
                                      np.asarray(b._value), err_msg=n)

    opt2 = paddle.optimizer.Adam(parameters=m2.parameters())
    opt2.set_state_dict(paddle.load(str(tmp_path / "ckpt" / "opt.pdopt")))
    assert opt2._step_count == opt._step_count


def test_dist_checkpoint_roundtrip_plain(tmp_path):
    """Unsharded tensors roundtrip through the sharded format."""
    m = _mlp()
    path = str(tmp_path / "dc")
    dist.checkpoint.save_state_dict(m.state_dict(), path)
    assert os.path.exists(os.path.join(path, "0.metadata"))

    m2 = _mlp()
    sd = m2.state_dict()
    dist.checkpoint.load_state_dict(sd, path)
    for (n, a), (_, b) in zip(m.named_parameters(), m2.named_parameters()):
        np.testing.assert_array_equal(np.asarray(a._value),
                                      np.asarray(b._value), err_msg=n)


def test_dist_checkpoint_sharded_reshard(tmp_path):
    """Save from an mp=4 sharded model, load into an mp-free copy (and
    back) — shards are reassembled and resharded on load."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                               "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    from paddle_tpu.distributed.fleet.layers import mpu

    class TP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = mpu.ColumnParallelLinear(16, 32,
                                                gather_output=False)
            self.fc2 = mpu.RowParallelLinear(32, 16,
                                             input_is_parallel=True)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    paddle.seed(3)
    model = TP()
    opt = paddle.optimizer.Adam(parameters=model.parameters())
    eng = ParallelEngine(model, opt, hcg.mesh)  # physically shards params

    # fc1 weight is mp-sharded over 4 devices now
    w = model.fc1.weight._value
    assert not w.sharding.is_fully_replicated

    path = str(tmp_path / "dc_sharded")
    dist.checkpoint.save_state_dict(
        {"model": model.state_dict()}, path)

    # metadata must record 4 shards for the column weight
    import json

    with open(os.path.join(path, "0.metadata")) as f:
        md = json.load(f)
    key = [k for k in md["state_dict_metadata"] if "fc1" in k and
           k.endswith("weight")][0]
    assert len(md["state_dict_metadata"][key]) == 4

    # load into a fresh sharded model — values must match the original
    paddle.seed(99)
    model2 = TP()
    opt2 = paddle.optimizer.Adam(parameters=model2.parameters())
    eng2 = ParallelEngine(model2, opt2, hcg.mesh)
    sd = {"model": model2.state_dict()}
    dist.checkpoint.load_state_dict(sd, path)
    for (n, a), (_, b) in zip(model.named_parameters(),
                              model2.named_parameters()):
        np.testing.assert_array_equal(np.asarray(a._value),
                                      np.asarray(b._value), err_msg=n)
    # and the loaded weight kept its sharded placement
    assert not model2.fc1.weight._value.sharding.is_fully_replicated


def test_load_assembles_only_addressable_windows(monkeypatch, tmp_path):
    """Shard-local load (VERDICT item 6): a sharded target tensor is
    filled via shard-sized windows, never a full-size host buffer."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    import importlib

    L = importlib.import_module(
        "paddle_tpu.distributed.checkpoint.load_state_dict")
    from jax.sharding import NamedSharding, PartitionSpec as P
    import jax

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 8}
    hcg = fleet.init(is_collective=True, strategy=strategy)

    w = paddle.to_tensor(np.arange(64 * 32, dtype="float32").reshape(64, 32))
    save_state_dict({"w": w}, str(tmp_path))

    sh = NamedSharding(hcg.mesh, P("mp", None))
    tgt = paddle.to_tensor(np.zeros((64, 32), "float32"))
    tgt._value = jax.device_put(tgt._value, sh)

    sizes = []
    orig = L._window

    def spy(md, storages, key, metas, gshape, dtype, sl):
        out = orig(md, storages, key, metas, gshape, dtype, sl)
        sizes.append(out.size)
        return out

    monkeypatch.setattr(L, "_window", spy)
    load_state_dict({"w": tgt}, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(tgt._value),
                                  np.asarray(w._value))
    assert sizes and max(sizes) <= 64 * 32 // 8, sizes
