"""Eager collective API surface: the holes VERDICT r1 flagged.

(reference surface: python/paddle/distributed/communication/ — every
entry point works, none raises NotImplementedError.)
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.core.enforce import PreconditionNotMetError


def _mesh(n=4, name="x"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _run_spmd(mesh, fn, x, in_spec, out_spec):
    from paddle_tpu.distributed.engine import _shard_map

    def wrapped(v):
        with dist.spmd_region():
            t = paddle.Tensor(v, stop_gradient=True)
            out = fn(t)
            return out._value if hasattr(out, "_value") else out

    return np.asarray(jax.jit(_shard_map(
        wrapped, mesh, (in_spec,), out_spec))(x))


def test_reduce_prod_negatives_and_zeros():
    mesh = _mesh(4)
    g = dist.new_group(axis_names=("x",), nranks=4)
    vals = np.array([2.0, -3.0, 4.0, -5.0], np.float32)
    out = _run_spmd(mesh, lambda t: dist.all_reduce(t, op=dist.ReduceOp.PROD,
                                                    group=g),
                    vals, P("x"), P("x"))
    np.testing.assert_allclose(out, np.full(4, 120.0), rtol=1e-5)
    # odd number of negatives
    vals = np.array([2.0, -3.0, 4.0, 5.0], np.float32)
    out = _run_spmd(mesh, lambda t: dist.all_reduce(t, op=dist.ReduceOp.PROD,
                                                    group=g),
                    vals, P("x"), P("x"))
    np.testing.assert_allclose(out, np.full(4, -120.0), rtol=1e-5)
    # any zero -> 0
    vals = np.array([2.0, 0.0, 4.0, -5.0], np.float32)
    out = _run_spmd(mesh, lambda t: dist.all_reduce(t, op=dist.ReduceOp.PROD,
                                                    group=g),
                    vals, P("x"), P("x"))
    np.testing.assert_allclose(out, np.zeros(4), atol=1e-6)


def test_all_gather_axis_nonzero():
    mesh = _mesh(4)
    g = dist.new_group(axis_names=("x",), nranks=4)
    x = np.arange(4 * 2 * 3, dtype=np.float32).reshape(4, 2, 3)

    def fn(t):
        parts = []
        out = dist.all_gather(parts, t, group=g, axis=1)
        # tensor_list entries must be the per-rank slices along `axis`
        assert len(parts) == 4
        assert tuple(parts[0].shape) == (1, 2, 3)
        return out

    out = _run_spmd(mesh, fn, x, P("x"), P("x", None, None))
    # each rank gathers all 4 shards along axis=1: local (1,8,3)
    assert out.shape == (4, 8, 3)


def test_axisless_rank_group_fails_loudly_in_spmd():
    mesh = _mesh(4)
    dist.init_parallel_env()
    g = dist.new_group(ranks=[0, 1])
    with pytest.raises(Exception) as ei:
        _run_spmd(mesh, lambda t: dist.all_reduce(t, group=g),
                  np.ones(4, np.float32), P("x"), P("x"))
    assert "mesh ax" in str(ei.value) or "axis" in str(ei.value)


def test_split_group_over_mesh_axis():
    dist.collective._world.initialized = False
    dist.init_parallel_env(Mesh(np.array(jax.devices()[:8]), ("world",)))
    parent = dist.get_group(0)
    sub = dist.split_group(parent, 4)
    assert sub.nranks == 4
    assert sub.axis_names  # device-collective capable
    mesh = dist.collective.get_world_mesh()
    assert sub.axis_names[0] in mesh.axis_names
    assert mesh.shape[sub.axis_names[0]] == 4
    # the parent/world group must STILL be collective-capable after the
    # mesh refactor: its axis was rewritten onto the (outer, inner) pair
    assert all(a in mesh.axis_names for a in parent.axis_names)
    x = np.ones(8, np.float32)
    out = _run_spmd(mesh, lambda t: dist.all_reduce(t, group=parent),
                    x, P(parent.axis_names), P(parent.axis_names))
    np.testing.assert_allclose(out, np.full(8, 8.0))
    # and the subgroup reduces over its 4 members only
    vals = np.arange(8, dtype=np.float32)
    out = _run_spmd(mesh, lambda t: dist.all_reduce(t, group=sub),
                    vals, P(parent.axis_names), P(parent.axis_names))
    np.testing.assert_allclose(out, np.array([6, 6, 6, 6, 22, 22, 22, 22],
                                             np.float32))


def test_send_recv_single_process_loopback():
    t = paddle.to_tensor(np.arange(4, dtype=np.float32))
    task = dist.isend(t, dst=0)
    assert task.is_completed()
    r = paddle.to_tensor(np.zeros(4, dtype=np.float32))
    dist.irecv(r, src=0).wait()
    np.testing.assert_allclose(np.asarray(r._value),
                               np.arange(4, dtype=np.float32))


def test_send_recv_rejected_inside_spmd():
    mesh = _mesh(2)
    with pytest.raises(PreconditionNotMetError):
        _run_spmd(mesh, lambda t: dist.send(t, dst=1),
                  np.ones(2, np.float32), P("x"), P("x"))


def test_broadcast_object_list_single_process():
    objs = [{"a": 1}]
    dist.broadcast_object_list(objs, src=0)
    assert objs == [{"a": 1}]


def test_all_gather_object_single_process():
    out = []
    dist.all_gather_object(out, {"r": 0})
    assert out == [{"r": 0}]
