"""Traced GradScaler protocol inside the compiled engine step
(reference: python/paddle/distributed/fleet/meta_optimizers/
dygraph_optimizer/hybrid_parallel_gradscaler.py — found_inf allreduced
across every parallel group, update skipped on overflow; here the whole
protocol is carried device state inside ONE jitted step)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.engine import ParallelEngine


def _mlp(d=8, h=16):
    class MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = paddle.nn.Linear(d, h)
            self.fc2 = paddle.nn.Linear(h, d)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    return MLP()


def _loss_fn(model, batch):
    out = model(batch["x"])
    return paddle.mean((out - batch["y"]) ** 2)


def _init_hybrid(dp=2):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    return fleet.init(is_collective=True, strategy=strategy)


def test_scaler_parity_on_clean_data():
    """scale/unscale must cancel exactly: scaled run == unscaled run."""
    _init_hybrid(dp=2)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 8)).astype(np.float32)
    y = rng.standard_normal((4, 8)).astype(np.float32)

    losses = {}
    for use_scaler in (False, True):
        paddle.seed(7)
        model = _mlp()
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=model.parameters())
        eng = ParallelEngine(model, opt)
        scaler = paddle.amp.GradScaler(
            init_loss_scaling=2.0 ** 10) if use_scaler else None
        step = eng.train_step(_loss_fn, scaler=scaler)
        ls = [float(step({"x": x, "y": y})) for _ in range(4)]
        losses[use_scaler] = ls
    np.testing.assert_allclose(losses[False], losses[True],
                               rtol=2e-5, atol=2e-6)


def test_scaler_skips_on_inf_and_decays_scale():
    """An injected inf must (a) leave params+opt states untouched,
    (b) decay the scale, (c) be visible via last_found_inf — and the
    next clean step must resume training."""
    _init_hybrid(dp=2)
    paddle.seed(11)
    model = _mlp()
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())
    eng = ParallelEngine(model, opt)
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 8,
                                   decr_every_n_nan_or_inf=1)
    step = eng.train_step(_loss_fn, scaler=scaler)

    rng = np.random.default_rng(1)
    x = rng.standard_normal((4, 8)).astype(np.float32)
    y = rng.standard_normal((4, 8)).astype(np.float32)

    l0 = float(step({"x": x, "y": y}))
    assert not scaler.last_found_inf
    params_before = [np.asarray(p._value) for p in model.parameters()]
    m_before = [np.asarray(opt._states[id(p)]["moment1"])
                for p in model.parameters() if p.trainable]

    bad_x = x.copy()
    bad_x[0, 0] = np.inf
    bad_loss = step({"x": bad_x, "y": y})
    assert scaler.last_found_inf
    for p, before in zip(model.parameters(), params_before):
        np.testing.assert_array_equal(np.asarray(p._value), before)
    for p, before in zip([p for p in model.parameters() if p.trainable],
                         m_before):
        np.testing.assert_array_equal(
            np.asarray(opt._states[id(p)]["moment1"]), before)
    assert scaler.get_loss_scaling() == pytest.approx(2.0 ** 7)

    l2 = float(step({"x": x, "y": y}))
    assert not scaler.last_found_inf
    assert np.isfinite(l2) and l2 < l0
    for p, before in zip(model.parameters(), params_before):
        assert not np.array_equal(np.asarray(p._value), before)


def test_scaler_growth_after_n_good_steps():
    _init_hybrid(dp=1)
    paddle.seed(5)
    model = _mlp()
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())
    eng = ParallelEngine(model, opt)
    scaler = paddle.amp.GradScaler(init_loss_scaling=64.0,
                                   incr_every_n_steps=3)
    step = eng.train_step(_loss_fn, scaler=scaler)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, 8)).astype(np.float32)
    y = rng.standard_normal((2, 8)).astype(np.float32)
    for _ in range(3):
        step({"x": x, "y": y})
    assert scaler.get_loss_scaling() == pytest.approx(128.0)
    state = scaler.state_dict()
    assert state["good_steps"] == 0


def test_eager_scaler_found_inf_still_works():
    """Eager (non-engine) GradScaler path: overflow detection + skip."""
    paddle.seed(3)
    model = _mlp()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0,
                                   decr_every_n_nan_or_inf=1)
    x = paddle.to_tensor(np.full((2, 8), np.inf, dtype=np.float32))
    y = paddle.to_tensor(np.zeros((2, 8), dtype=np.float32))
    loss = paddle.mean((model(x) - y) ** 2)
    scaled = scaler.scale(loss)
    scaled.backward()
    w0 = np.asarray(model.fc1.weight._value)
    scaler.step(opt)
    np.testing.assert_array_equal(np.asarray(model.fc1.weight._value), w0)
    assert scaler.get_loss_scaling() == pytest.approx(4.0)
