"""Host-side RPC over the native TCPStore agent (reference:
python/paddle/distributed/rpc over the brpc agent)."""
import pytest
import json
import os
import socket
import subprocess
import sys

pytestmark = pytest.mark.slow  # multi-process / long-convergence; quick suite = -m 'not slow'


_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_WORKER = os.path.join(_REPO, "tests", "workers", "rpc_worker.py")


def test_rpc_two_workers(tmp_path):
    # one retry: the 2-proc bootstrap occasionally starves under heavy
    # host CPU oversubscription (passes reliably alone)
    try:
        _run_rpc_pair(tmp_path / "a")
    except (subprocess.TimeoutExpired, AssertionError):
        _run_rpc_pair(tmp_path / "b")


def _run_rpc_pair(tmp_path):
    os.makedirs(str(tmp_path), exist_ok=True)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env["OMP_NUM_THREADS"] = "1"
        env["OPENBLAS_NUM_THREADS"] = "1"
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS_NUM"] = "2"
        env["PADDLE_MASTER"] = f"127.0.0.1:{port}"
        env["TEST_OUT"] = str(tmp_path / "rpc")
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER], env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    for p in procs:
        # 300s: the 2-proc bootstrap is slow under full-suite CPU
        # oversubscription (observed flaking at 120s)
        out, _ = p.communicate(timeout=300)
        assert p.returncode == 0, out.decode(errors="replace")[-2000:]
    for rank in range(2):
        with open(str(tmp_path / "rpc") + f".{rank}") as f:
            r = json.load(f)
        assert r["sync"] == rank + 10
        assert r["async"] == [0, 2, 4, 6]
        assert r["peer_rank"] == 1 - rank
        assert r["all"] == ["worker0", "worker1"]
        assert r["exc"] == "remote boom"
        # the fn executed in the PEER's process, not ours
        assert r["self_env"] == str(1 - rank)
