"""Shared test helpers: the OpTest pattern (reference:
test/legacy_test/op_test.py:420 — numpy-reference forward check
(check_output :2765) + numeric-differentiation grad check (check_grad
:2975))."""
import numpy as np

import paddle_tpu as paddle


def check_output(fn, np_fn, arrays, rtol=1e-5, atol=1e-6, **kwargs):
    """Run op on Tensors and compare against a numpy reference."""
    tensors = [paddle.to_tensor(a) for a in arrays]
    out = fn(*tensors, **kwargs)
    ref = np_fn(*arrays, **kwargs)
    if isinstance(out, (list, tuple)):
        for o, r in zip(out, ref):
            np.testing.assert_allclose(o.numpy(), r, rtol=rtol, atol=atol)
    else:
        np.testing.assert_allclose(out.numpy(), ref, rtol=rtol, atol=atol)
    return out


def check_grad(fn, arrays, eps=1e-3, rtol=1e-2, atol=1e-3, **kwargs):
    """Numeric gradient check of sum(fn(*args)) wrt each float input."""
    tensors = [paddle.to_tensor(a, stop_gradient=False) for a in arrays]
    out = fn(*tensors, **kwargs)
    loss = out.sum() if not isinstance(out, (list, tuple)) else sum(
        o.sum() for o in out)
    loss.backward()

    for i, a in enumerate(arrays):
        if not np.issubdtype(np.asarray(a).dtype, np.floating):
            continue
        a = np.asarray(a, dtype=np.float64)
        num_grad = np.zeros_like(a)
        it = np.nditer(a, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            ap = a.copy(); ap[idx] += eps
            am = a.copy(); am[idx] -= eps

            def run(val):
                args = [paddle.to_tensor(np.asarray(
                    val if j == i else arrays[j], dtype=np.float32))
                    for j in range(len(arrays))]
                o = fn(*args, **kwargs)
                if isinstance(o, (list, tuple)):
                    return float(sum(x.sum() for x in o).numpy())
                return float(o.sum().numpy())

            num_grad[idx] = (run(ap) - run(am)) / (2 * eps)
            it.iternext()
        got = tensors[i].grad.numpy()
        np.testing.assert_allclose(got, num_grad, rtol=rtol, atol=atol,
                                   err_msg=f"grad mismatch on input {i}")


def kill_and_reap(procs, grace=10):
    """Kill every subprocess in ``procs`` and reap it (closing its
    pipes) so a retrying multi-process test leaves no zombies behind.
    The one shared copy of the kill/reap half of the retry-once
    pattern used by test_multiprocess / test_rpc / test_elastic_resume."""
    for q in procs:
        q.kill()
    for q in procs:
        try:
            q.communicate(timeout=grace)
        except Exception:
            pass


def retry_once(fn, *exc_types):
    """Run ``fn()``; on one of ``exc_types`` (default TimeoutExpired)
    run it once more (the loaded-CI flake guard; the second failure
    propagates so deterministic breakage still fails)."""
    import subprocess as _sp

    exc = exc_types or (_sp.TimeoutExpired,)
    try:
        return fn()
    except exc:
        return fn()
