"""Test harness: force the XLA CPU backend with 8 virtual devices so the
multi-chip sharding paths are exercised without TPU hardware (the
reference's fake_cpu_device / gloo-backend strategy, SURVEY.md §4).

NOTE: the environment's sitecustomize force-selects the 'axon' TPU
platform via jax.config, so setting JAX_PLATFORMS alone is not enough —
we must update jax.config before any backend initialises.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
