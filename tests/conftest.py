"""Test harness: force the XLA CPU backend with 8 virtual devices so the
multi-chip sharding paths are exercised without TPU hardware (the
reference's fake_cpu_device / gloo-backend strategy, SURVEY.md §4).

NOTE: the environment's sitecustomize force-selects the 'axon' TPU
platform via jax.config, so setting JAX_PLATFORMS alone is not enough —
we must update jax.config before any backend initialises.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _reset_fleet_per_module():
    """Isolate test modules from each other's fleet topology: a module
    that never calls fleet.init must see single-device behavior even if
    a previously-run module initialized a hybrid mesh (the reference gets
    this isolation for free from per-test subprocesses)."""
    from paddle_tpu.distributed import fleet as _fleet

    _fleet._fleet_state.update(initialized=False, hcg=None, strategy=None)
    yield
