"""Benchmarks for the BASELINE.md configs, one JSON line each.

Covered rows (BASELINE.md):
  1. ResNet-50 single chip ............ imgs/sec            (train step)
  2. GPT-3 1.3B Fleet TP .............. tokens/sec/chip, MFU (headline,
     printed LAST so single-line parsers keep seeing it)
  4. ERNIE-MoE style GPT-MoE .......... tokens/sec/chip
  5. Llama-7B generation .............. decode tokens/sec, ms/token
     (compiled prefill + single-XLA-program scan decode, Pallas
     decode-attention kernel, ctx 2048)
Row 3 (13B hybrid TP*PP*DP) needs real multi-chip hardware - TBD.

MFU = 6*N*tok_s/peak (recompute FLOPs excluded, so remat lowers measured
MFU honestly); vs_baseline for the MFU line is measured/0.45 (the
north-star target — the reference publishes no absolute numbers,
BASELINE.md). The decode line's vs_baseline is the fraction of the
HBM-bandwidth roofline (params_bytes / BW per token) achieved.

On CPU (no TPU attached) runs tiny smoke configs so the bench always
produces lines.
"""
import json
import sys
import time

import numpy as np

# Peak dense bf16 FLOPs and HBM bandwidth per chip by TPU generation
# (public specs).
_PEAK = {
    "v4": (275e12, 1.2e12),
    "v5e": (197e12, 0.819e12), "v5 lite": (197e12, 0.819e12),
    "v5litepod": (197e12, 0.819e12),
    "v5p": (459e12, 2.765e12),
    "v6e": (918e12, 1.64e12), "v6 lite": (918e12, 1.64e12),
}


def _chip(device):
    kind = str(getattr(device, "device_kind", "")).lower()
    for k, v in _PEAK.items():
        if k in kind:
            return v
    if "tpu" in str(getattr(device, "platform", "")).lower():
        return _PEAK["v5p"]  # unknown generation: assume v5p
    return (0.0, 0.0)  # CPU: MFU not meaningful


def _emit(payload):
    print(json.dumps(payload), flush=True)


def _telemetry_section():
    """Compact snapshot of the unified observability registry
    (paddle_tpu/observability) — bench lines carry the SAME metrics a
    live scrape would see: histograms as count/p50/p99, counters and
    gauges as values. Each bench runs in its own process, so the
    registry holds exactly that bench's run."""
    try:
        from paddle_tpu.observability import get_registry

        snap = get_registry().snapshot()
    except Exception:
        return {}
    out = {}
    for name, entry in sorted(snap["metrics"].items()):
        short = name.replace("paddle_tpu_", "", 1)
        for row in entry["series"]:
            lbl = ",".join(f"{k}={v}"
                           for k, v in sorted(row["labels"].items()))
            key = short + (f"{{{lbl}}}" if lbl else "")
            if entry["type"] == "histogram":
                if row["count"]:
                    out[key] = {"count": row["count"],
                                "p50": round(row["p50"], 6),
                                "p99": round(row["p99"], 6)}
            else:
                v = row["value"]
                out[key] = round(v, 6) if isinstance(v, float) else v
    return out


# ---------------------------------------------------------------------------
# 1. ResNet-50 (BASELINE row 1)
# ---------------------------------------------------------------------------
def bench_resnet(on_tpu, dev):
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu import nn
    from paddle_tpu.vision.models import resnet18, resnet50

    if on_tpu:
        model_fn, B, steps = resnet50, 256, 5
    else:
        model_fn, B, steps = resnet18, 8, 2

    paddle.seed(0)
    model = model_fn(num_classes=1000 if on_tpu else 10)
    if on_tpu:
        model.astype("bfloat16")
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(
        lambda m, b: nn.functional.cross_entropy(m(b["x"]), b["y"]))

    r = np.random.RandomState(0)
    hw = 224 if on_tpu else 32
    batch = {
        "x": paddle.to_tensor(
            r.rand(B, 3, hw, hw).astype(
                "float32" if not on_tpu else "bfloat16")),
        "y": paddle.to_tensor(r.randint(0, 1000 if on_tpu else 10, (B,))),
    }
    loss = step(batch)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(batch)
    float(loss)
    dt = time.perf_counter() - t0
    imgs_s = B * steps / dt
    _emit({
        "metric": "resnet50_train_imgs_per_sec" if on_tpu
        else "resnet_smoke_imgs_per_sec",
        "value": round(imgs_s, 2),
        "unit": "imgs/s",
        "vs_baseline": 0.0,  # reference publishes no number (BASELINE.md)
        "batch": B,
        "device": str(getattr(dev, "device_kind", dev.platform)),
    })


# ---------------------------------------------------------------------------
# 4. GPT-MoE (ERNIE-MoE style, BASELINE row 4)
# ---------------------------------------------------------------------------
def bench_moe(on_tpu, dev):
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=12,
                        num_heads=16, max_position_embeddings=1024,
                        dtype="bfloat16", num_experts=8, moe_every=2)
        B, S, steps = 8, 1024, 5
    else:
        from paddle_tpu.models import gpt_moe_tiny

        cfg = gpt_moe_tiny()
        B, S, steps = 4, 16, 2

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 state_dtype="bfloat16" if on_tpu else None)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(
        lambda m, b: crit(m(b["x"]), b["y"]) + m.aux_loss)

    r = np.random.RandomState(0)
    ids = r.randint(0, cfg.vocab_size, (B, S + 1))
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}
    loss = step(batch)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(batch)
    float(loss)
    dt = time.perf_counter() - t0
    tok_s = B * S * steps / dt
    _emit({
        "metric": "gpt_moe_train_tokens_per_sec" if on_tpu
        else "moe_smoke_tokens_per_sec",
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": 0.0,  # reference publishes no number (BASELINE.md)
        "num_experts": cfg.num_experts,
        "device": str(getattr(dev, "device_kind", dev.platform)),
    })


# ---------------------------------------------------------------------------
# 5. Llama-7B generation (BASELINE row 5)
# ---------------------------------------------------------------------------
def bench_llama_decode(on_tpu, dev, weight_only=False):
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_7b, \
        llama_tiny

    peak, hbm_bw = _chip(dev)
    old_dtype = paddle.get_default_dtype()
    if on_tpu:
        paddle.set_default_dtype("bfloat16")
        cfg = llama_7b(max_position_embeddings=2304, dtype="bfloat16")
        S_ctx, n_new = 2048, 128
    else:
        cfg = llama_tiny()
        S_ctx, n_new = 24, 8
    try:
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        conf = Config().set_model(model)
        if weight_only:
            conf.enable_weight_only("weight_only_int8")
        pred = create_predictor(conf)
        r = np.random.RandomState(0)
        prompt = paddle.to_tensor(
            r.randint(0, cfg.vocab_size, (1, S_ctx)))

        # warm both programs, then time prefill-only and prefill+decode
        float(pred.generate(prompt, max_new_tokens=1)._value[0, -1])
        float(pred.generate(prompt, max_new_tokens=n_new)._value[0, -1])
        t0 = time.perf_counter()
        out = pred.generate(prompt, max_new_tokens=1)
        float(out._value[0, -1])
        t_prefill = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = pred.generate(prompt, max_new_tokens=n_new)
        float(out._value[0, -1])
        t_full = time.perf_counter() - t0
        dec_s = max(t_full - t_prefill, 1e-4)
        tok_s = (n_new - 1) / dec_s
        ms_tok = dec_s / (n_new - 1) * 1e3
        # decode is HBM-bound: roofline = BW / bytes-touched-per-token.
        # vs_baseline is ALWAYS the bf16 (2-byte) roofline fraction, so
        # the int8 line shows its win as a fraction > the fp line's
        # (most weights are then 1 byte; the lm_head stays fp).
        n_params = cfg.num_params()
        roofline = (hbm_bw / (2.0 * n_params)) if hbm_bw else 0.0
        name = "llama7b_decode_tokens_per_sec" if on_tpu \
            else "llama_smoke_decode_tokens_per_sec"
        if weight_only:
            name += "_int8"
        _emit({
            "metric": name,
            "value": round(tok_s, 2),
            "unit": "tokens/s",
            "vs_baseline": round(tok_s / roofline, 4) if roofline else 0.0,
            "ms_per_token": round(ms_tok, 2),
            "prefill_s": round(t_prefill, 3),
            "context": S_ctx,
            "params": n_params,
            "device": str(getattr(dev, "device_kind", dev.platform)),
        })
    finally:
        paddle.set_default_dtype(old_dtype)


# ---------------------------------------------------------------------------
# 5b. Ragged serving: B=8 mixed prompt lengths, paged KV cache, per-row
# offsets (the continuous-batching decode the reference serves with
# block_multi_head_attention). int8 weights so 7B + the B=8 pool fits
# v5e HBM.
# ---------------------------------------------------------------------------
def bench_llama_decode_ragged(on_tpu, dev):
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_7b, \
        llama_tiny

    peak, hbm_bw = _chip(dev)
    old_dtype = paddle.get_default_dtype()
    if on_tpu:
        paddle.set_default_dtype("bfloat16")
        cfg = llama_7b(max_position_embeddings=2304, dtype="bfloat16")
        lens = [1024, 896, 768, 640, 512, 384, 320, 256]
        n_new, page = 64, 128
    else:
        cfg = llama_tiny()
        lens = [24, 17, 11, 9]
        n_new, page = 8, 8
    B = len(lens)
    try:
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        conf = Config().set_model(model).enable_paged_kv(page_size=page)
        if on_tpu:
            conf.enable_weight_only("weight_only_int8")
        pred = create_predictor(conf)
        r = np.random.RandomState(0)
        S0 = max(lens)
        ids = np.zeros((B, S0), np.int64)
        for b, L in enumerate(lens):
            ids[b, :L] = r.randint(1, cfg.vocab_size, (L,))
        prompt = paddle.to_tensor(ids)
        ln = np.asarray(lens, np.int32)

        float(pred.generate(prompt, max_new_tokens=1,
                            lengths=ln)._value[0, -1])       # warm prefill
        float(pred.generate(prompt, max_new_tokens=n_new,
                            lengths=ln)._value[0, -1])       # warm decode
        t0 = time.perf_counter()
        out = pred.generate(prompt, max_new_tokens=1, lengths=ln)
        float(out._value[0, -1])
        t_prefill = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = pred.generate(prompt, max_new_tokens=n_new, lengths=ln)
        float(out._value[0, -1])
        dec_s = max(time.perf_counter() - t0 - t_prefill, 1e-4)
        tok_s = B * (n_new - 1) / dec_s
        n_params = cfg.num_params()
        # single-row bf16 weight roofline: batching + paging should put
        # aggregate tokens/s well ABOVE 1.0x of it
        roofline = (hbm_bw / (2.0 * n_params)) if hbm_bw else 0.0
        _emit({
            "metric": "llama7b_ragged_paged_decode_tokens_per_sec"
            if on_tpu else "llama_smoke_ragged_paged_decode_tokens_per_sec",
            "value": round(tok_s, 2),
            "unit": "tokens/s",
            "vs_baseline": round(tok_s / roofline, 4) if roofline else 0.0,
            "batch": B, "page_size": page,
            "mixed_lengths": [int(x) for x in lens],
            "prefill_s": round(t_prefill, 3),
            "device": str(getattr(dev, "device_kind", dev.platform)),
        })
    finally:
        paddle.set_default_dtype(old_dtype)


# ---------------------------------------------------------------------------
# 5c. Continuous-batching serving engine over the ragged paged KV cache:
# a mixed-length request stream through ServingEngine (admission /
# eviction / backfill, one shared decode program) vs the same stream
# served sequentially, one request per Predictor.generate. The JSON
# line carries the compile-cache counters: after warmup on one length
# mix, the streamed mixes must add ZERO compiles (program reuse is the
# tracked metric, not just tokens/s).
# ---------------------------------------------------------------------------
def bench_serving_mixed(on_tpu, dev):
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.inference import Config, ServingEngine, \
        create_predictor
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_7b, \
        llama_tiny
    from paddle_tpu.observability import timeseries as _ts

    old_dtype = paddle.get_default_dtype()
    if on_tpu:
        paddle.set_default_dtype("bfloat16")
        cfg = llama_7b(max_position_embeddings=2304, dtype="bfloat16")
        warm_mix = [512, 768]
        mixes = [[1024, 896, 640], [512, 384], [768, 320, 256, 640],
                 [896]]
        n_new, page, B, chunk = 64, 128, 8, 8
    else:
        cfg = llama_tiny()
        warm_mix = [7, 12]
        mixes = [[24, 17, 11], [9, 5], [30, 2, 14, 8], [13]]
        n_new, page, B, chunk = 8, 8, 4, 4
    try:
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        conf = Config().set_model(model).enable_paged_kv(page_size=page)
        if on_tpu:
            conf.enable_weight_only("weight_only_int8")
        pred = create_predictor(conf)
        r = np.random.RandomState(0)

        def prompts(lens):
            return [r.randint(1, cfg.vocab_size, (L,)) for L in lens]

        # mem_ledger=True: per-executable HBM attribution (prefill per
        # bucket + the shared decode) rides the line below; the
        # recompiles_after_warmup field still gates at 0 with it on
        eng = ServingEngine(pred, max_batch=B, decode_chunk=chunk,
                            mem_ledger=True)
        # durable metrics journal riding alongside (observability/
        # timeseries): the background sampler snapshots the same
        # registry the scrape reads — host-side file IO only, so the
        # recompiles_after_warmup field below still gates at 0 with it
        # attached for the whole measured stream
        ts_smp = _ts.attach_dir(
            tempfile.mkdtemp(prefix="timeseries_serving_"),
            interval_s=0.5)
        for p in prompts(warm_mix):                      # warmup mix
            eng.submit(p, max_new_tokens=n_new)
        eng.run()
        compiles_warm = eng.stats.compiles
        stream = [p for mix in mixes for p in prompts(mix)]
        t0 = time.perf_counter()
        rids = [eng.submit(p, max_new_tokens=n_new) for p in stream]
        done = eng.run()
        dt = max(time.perf_counter() - t0, 1e-4)
        n_tok = sum(len(done[rid].new_tokens) for rid in rids)
        tok_s = n_tok / dt

        # sequential per-request Predictor baseline on the SAME stream
        seq_pred = create_predictor(conf)
        for p in prompts(warm_mix):                      # warm its programs
            seq_pred.generate(paddle.to_tensor(p[None]),
                              max_new_tokens=n_new)
        t0 = time.perf_counter()
        for p in stream:
            out = seq_pred.generate(paddle.to_tensor(p[None]),
                                    max_new_tokens=n_new)
        float(out._value[0, -1])
        seq_dt = max(time.perf_counter() - t0, 1e-4)
        seq_tok_s = len(stream) * n_new / seq_dt

        # the latency percentiles come from the SAME registry a live
        # scrape would read (ServingEngine's TTFT/TPOT histograms)
        snap = eng.metrics_snapshot()["metrics"]

        def _hist(name, q):
            rows = snap[name]["series"]
            return round(rows[0][q], 6) if rows else 0.0

        # per-request lifecycle span percentiles (queued / prefill /
        # decode / e2e) from the bounded trace ring's stage histogram
        spans = {
            row["labels"]["stage"]: {"count": row["count"],
                                     "p50": round(row["p50"], 6),
                                     "p99": round(row["p99"], 6)}
            for row in snap["paddle_tpu_serving_request_stage_seconds"]
            ["series"]}

        # HBM memory ledger + roofline verdict for the serving engine:
        # per-executable byte classes, resident state (params + KV
        # page pool), and the decode round's compute/HBM/ICI bound
        mem = eng.memory_summary()
        roof = eng.roofline_report()

        # close the journal with one guaranteed final sample, then pin
        # the per-sample overhead: bounded host-side cost (snapshot +
        # one flushed JSONL line), never device work
        ts_smp.sample_now()
        ts_stats = ts_smp.stats()
        ts_smp.close()
        assert ts_stats["samples"] >= 1, ts_stats
        assert (ts_stats["overhead_seconds"]
                <= 0.25 * ts_stats["samples"]), ts_stats

        _emit({
            "metric": "serving_mixed_traffic_tokens_per_sec" if on_tpu
            else "serving_smoke_mixed_traffic_tokens_per_sec",
            "value": round(tok_s, 2),
            "unit": "tokens/s",
            # the gate: continuous batching must beat sequential serving
            "vs_baseline": round(tok_s / seq_tok_s, 4),
            "sequential_tokens_per_sec": round(seq_tok_s, 2),
            "ttft_p50_s": _hist("paddle_tpu_serving_ttft_seconds", "p50"),
            "ttft_p99_s": _hist("paddle_tpu_serving_ttft_seconds", "p99"),
            "tpot_p50_s": _hist("paddle_tpu_serving_tpot_seconds", "p50"),
            "tpot_p99_s": _hist("paddle_tpu_serving_tpot_seconds", "p99"),
            "compiles": eng.stats.compiles,
            "cache_hits": eng.stats.cache_hits,
            "recompiles_after_warmup": eng.stats.compiles - compiles_warm,
            "batch": B, "page_size": page, "decode_chunk": chunk,
            "requests": len(stream), "tokens": n_tok,
            "request_spans": spans,
            "request_traces": len(eng.traces),
            "memory": mem,
            "timeseries": {
                "samples": ts_stats["samples"],
                "journal_bytes": ts_stats["journal_bytes"],
                "overhead_seconds": round(
                    ts_stats["overhead_seconds"], 6)},
            "roofline": roof.to_dict(),
            "telemetry": _telemetry_section(),
            "device": str(getattr(dev, "device_kind", dev.platform)),
        })
        # memory-ledger exact gate: the measured KV pool bytes (shard
        # accounting over the live pool arrays) must equal the closed
        # form page_bytes x pool_pages (bench_compare _EXACT)
        st = mem["state"]
        ok = st["kv_pool_bytes"] == st["page_bytes"] * st["pool_pages"]
        _emit({"metric": "serving_mem_pool_parity",
               "value": 1.0 if ok else 0.0, "unit": "pass",
               "vs_baseline": 1.0 if ok else 0.0,
               "kv_pool_bytes": st["kv_pool_bytes"],
               "page_bytes": st["page_bytes"],
               "pool_pages": st["pool_pages"]})
        # sampler cost headline for the serving line (lower-better in
        # bench_compare): the metrics journal rides the whole measured
        # stream, and its wall cost must stay near zero
        _emit({"metric": "serving_mixed_sampler_overhead_seconds",
               "value": round(ts_stats["overhead_seconds"], 6),
               "unit": "s", "vs_baseline": 0.0,
               "samples": ts_stats["samples"],
               "journal_bytes": ts_stats["journal_bytes"],
               "seconds_per_sample": round(
                   ts_stats["overhead_seconds"]
                   / max(ts_stats["samples"], 1), 6)})
    finally:
        paddle.set_default_dtype(old_dtype)


# ---------------------------------------------------------------------------
# 5d. Chunked prefill vs head-of-line prefill under a Poisson
# mixed-length stream (the serving_mixed_traffic line's latency axis):
# long prompts are injected mid-decode into a stream of short requests,
# and the SAME arrival schedule is served twice — chunked prefill ON
# (prompts folded into the unified ragged [B, Sc] step, decode rows
# advancing every round) vs OFF (each arrival's prefill runs as its own
# program, stalling every in-flight decode row). The JSON lines carry
# TPOT p99 for both, the ragged-kernel parity gate, and the memledger
# comparison of the unified program's HBM traffic against the old
# prefill+decode two-program sum.
# ---------------------------------------------------------------------------
def bench_serving_chunked(on_tpu, dev):
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config, ServingEngine, \
        create_predictor
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_7b)

    old_dtype = paddle.get_default_dtype()
    if on_tpu:
        paddle.set_default_dtype("bfloat16")
        cfg = llama_7b(max_position_embeddings=2304, dtype="bfloat16")
        page, B, Sc = 128, 8, 256
        short_lens, long_len = (64, 96, 128), 1536
        n_short, n_long, new_s, new_l = 24, 3, 32, 16
        rate = 1.2                      # arrivals per decode round
    else:
        # the tiny smoke config with a longer position space so the
        # injected long prompts tower over the short stream (the HOL
        # contrast the line measures)
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          intermediate_size=128,
                          max_position_embeddings=512)
        page, B, Sc = 8, 4, 32
        short_lens, long_len = (6, 9, 12, 15), 192
        n_short, n_long, new_s, new_l = 18, 3, 12, 8
        rate = 0.8
    try:
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        conf = Config().set_model(model).enable_paged_kv(page_size=page)
        if on_tpu:
            conf.enable_weight_only("weight_only_int8")
        pred = create_predictor(conf)
        r = np.random.RandomState(7)

        # Poisson arrival schedule in decode-round time: short requests
        # stream steadily, long prompts land mid-decode (the HOL test)
        gaps = r.exponential(1.0 / rate, n_short)
        arrivals = [(float(t), int(r.choice(short_lens)), new_s)
                    for t in np.cumsum(gaps)]
        span = arrivals[-1][0]
        for k in range(n_long):
            arrivals.append((span * (k + 1.0) / (n_long + 1.0),
                             long_len, new_l))
        arrivals.sort()
        prompts = [(t, r.randint(1, cfg.vocab_size, (L,)), n)
                   for t, L, n in arrivals]

        def serve(chunked):
            eng = ServingEngine(
                pred, max_batch=B, mem_ledger=True,
                prefill_chunk=Sc if chunked else None)
            # warmup: one short + one long through every program shape
            for L in (short_lens[0], long_len):
                eng.submit(r.randint(1, cfg.vocab_size, (L,)),
                           max_new_tokens=2)
            eng.run()
            warm = eng.stats.compiles
            t0 = time.perf_counter()
            rnd, i = 0, 0
            while i < len(prompts) or eng.queue or eng.num_active:
                while i < len(prompts) and prompts[i][0] <= rnd:
                    _, ids, n = prompts[i]
                    eng.submit(ids, max_new_tokens=n)
                    i += 1
                eng.step()
                rnd += 1
            dt = max(time.perf_counter() - t0, 1e-4)
            tpots = [(q.t_finish - q.t_first_token)
                     / (len(q.new_tokens) - 1)
                     for q in eng.finished.values()
                     if len(q.new_tokens) > 1 and q.t_first_token]
            n_tok = sum(len(q.new_tokens) for q in eng.finished.values())
            return eng, {
                "tpot_p50_ms": round(float(np.percentile(tpots, 50))
                                     * 1e3, 3),
                "tpot_p99_ms": round(float(np.percentile(tpots, 99))
                                     * 1e3, 3),
                "tokens_per_sec": round(n_tok / dt, 2),
                "recompiles_after_warmup": eng.stats.compiles - warm,
                "rounds": rnd,
            }

        eng_on, on = serve(chunked=True)
        eng_off, off = serve(chunked=False)
        # the acceptance gate: the fixed lattice must absorb the whole
        # stream with ZERO post-warmup compiles in BOTH modes
        assert on["recompiles_after_warmup"] == 0, on
        assert off["recompiles_after_warmup"] == 0, off

        # memledger: the unified program's HBM traffic vs the old
        # prefill+decode two-program sum (measurable on chip; the CPU
        # backend has no memory_analysis and reports unknown)
        from paddle_tpu.core.bucketing import bucket as _bucket

        led_u = eng_on.memory_ledger(("unified", eng_on.Sc))
        led_p = eng_off.memory_ledger(
            ("prefill", min(_bucket(long_len), eng_off.M)))
        led_d = eng_off.memory_ledger(("decode",))
        if led_u is not None and led_u.available and \
                led_p is not None and led_p.available and \
                led_d is not None and led_d.available:
            two = led_p.traffic_bytes + led_d.traffic_bytes
            hbm = {"unified_traffic_bytes": int(led_u.traffic_bytes),
                   "two_program_traffic_bytes": int(two),
                   "unified_le_two_program":
                       bool(led_u.traffic_bytes <= two)}
        else:
            hbm = {"unified_le_two_program": "unknown (needs chips)"}

        _emit({
            "metric": "serving_mixed_traffic_tpot_p99_ms",
            "value": on["tpot_p99_ms"],
            "unit": "ms",
            # the gate: chunked prefill must hold the TPOT tail below
            # the head-of-line-blocking baseline on the same stream
            "vs_baseline": round(off["tpot_p99_ms"]
                                 / max(on["tpot_p99_ms"], 1e-9), 4),
            "chunked_on": on, "chunked_off": off,
            "prefill_chunk": Sc, "batch": B, "page_size": page,
            "long_prompt_len": long_len, "requests": len(prompts),
            "hbm": hbm,
            "telemetry": _telemetry_section(),
            "device": str(getattr(dev, "device_kind", dev.platform)),
        })

        # ragged-kernel parity gate (exact, bench_compare _EXACT): the
        # unified kernel vs its dense XLA fallback on a mixed batch
        # whose chunk straddles page boundaries — interpret mode off
        # chip, Mosaic on chip
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.ragged_paged_attention import (
            ragged_paged_attention, ragged_paged_attention_dense)

        B2, Sq, H, KV, D, pg, npg = 4, 16, 8, 2, 128, 8, 16
        P2 = B2 * npg + 5
        q = jnp.asarray(r.randn(B2, Sq, H, D), jnp.float32)
        kp = jnp.asarray(r.randn(P2, KV, pg, D), jnp.float32)
        vp = jnp.asarray(r.randn(P2, KV, pg, D), jnp.float32)
        tb = jnp.asarray(r.permutation(P2)[:B2 * npg].reshape(B2, npg),
                         jnp.int32)
        st = jnp.asarray([5, 77, 0, 0], jnp.int32)    # straddles pages
        nv = jnp.asarray([16, 1, 16, 0], jnp.int32)   # chunk/decode/dead
        diff = float(jnp.abs(
            ragged_paged_attention(q, kp, vp, tb, st, nv)
            - ragged_paged_attention_dense(q, kp, vp, tb, st, nv)).max())
        ok = diff < 1e-4
        _emit({"metric": "serving_ragged_kernel_parity",
               "value": 1.0 if ok else 0.0, "unit": "pass",
               "vs_baseline": 1.0 if ok else 0.0,
               "max_abs_diff": diff,
               "mode": "mosaic" if on_tpu else "interpret"})
    finally:
        paddle.set_default_dtype(old_dtype)


# ---------------------------------------------------------------------------
# 5e. Prefix-cache sharing + speculative decoding on the multi-tenant
# trace (the PR-16 serving lines): MANY users share a FEW long system
# prompts, so most arrivals' leading pages are already resident in the
# paged pool. The SAME Poisson trace is served three times — prefix
# cache ON, prefix cache OFF (the TTFT baseline), and prefix+spec ON
# (greedy draft-verify riding the unified [B, Sc] lattice) — and the
# JSON lines carry cache hit rate (ledger-exact fed+skipped
# accounting), TTFT p50/p99 on vs off, committed tokens per verify
# step, the exact three-way output-parity gate, and recompiles pinned
# at 0 for every mode (neither feature adds a program shape).
# ---------------------------------------------------------------------------
def bench_serving_prefix_spec(on_tpu, dev):
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config, ServingEngine, \
        create_predictor
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_7b)

    old_dtype = paddle.get_default_dtype()
    if on_tpu:
        paddle.set_default_dtype("bfloat16")
        cfg = llama_7b(max_position_embeddings=1024, dtype="bfloat16")
        page, B, Sc, k = 128, 8, 256, 4
        n_sys, sys_pages = 3, 4          # 3 system prompts x 512 tok
        n_users, tail_lo, tail_hi, n_new = 24, 32, 96, 32
        rate = 1.0
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          intermediate_size=128,
                          max_position_embeddings=256)
        page, B, Sc, k = 8, 4, 16, 3
        n_sys, sys_pages = 3, 4          # 3 system prompts x 32 tok
        n_users, tail_lo, tail_hi, n_new = 18, 4, 12, 8
        rate = 0.8
    try:
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        conf = Config().set_model(model).enable_paged_kv(page_size=page)
        if on_tpu:
            conf.enable_weight_only("weight_only_int8")
        pred = create_predictor(conf)
        # self-speculation draft (draft == target): the acceptance
        # CEILING, so tokens/step approaches k+1 while the propose /
        # verify / commit machinery (and its latency) stays realistic;
        # a distilled draft plugs into the same knob on chip
        dpred = create_predictor(
            Config().set_model(model).enable_paged_kv(page_size=page))
        r = np.random.RandomState(16)

        # multi-tenant trace: every request = one of n_sys shared
        # system prompts + a short unique user tail, Poisson arrivals
        sys_prompts = [r.randint(1, cfg.vocab_size,
                                 (sys_pages * page,))
                       for _ in range(n_sys)]
        gaps = r.exponential(1.0 / rate, n_users)
        trace = []
        for t in np.cumsum(gaps):
            sysp = sys_prompts[r.randint(n_sys)]
            tail = r.randint(1, cfg.vocab_size,
                             (r.randint(tail_lo, tail_hi),))
            trace.append((float(t), np.concatenate([sysp, tail])))
        total_prompt_tok = sum(len(p) for _, p in trace)

        def serve(prefix, spec):
            eng = ServingEngine(
                pred, max_batch=B, prefill_chunk=Sc,
                prefix_cache=prefix,
                draft_predictor=dpred if spec else None,
                spec_tokens=k if spec else 0)
            # warmup: one multi-chunk + one sub-chunk prompt through
            # every program shape (chunk feed, decode verify, propose)
            for L in (sys_pages * page + tail_lo, page - 2):
                eng.submit(r.randint(1, cfg.vocab_size, (L,)),
                           max_new_tokens=3)
            eng.run()
            warm = eng.stats.compiles
            rids, i, rnd = [], 0, 0
            t0 = time.perf_counter()
            while i < len(trace) or eng.queue or eng.num_active:
                while i < len(trace) and trace[i][0] <= rnd:
                    rids.append(eng.submit(trace[i][1],
                                           max_new_tokens=n_new))
                    i += 1
                eng.step()
                rnd += 1
            dt = max(time.perf_counter() - t0, 1e-4)
            fin = [eng.finished[rid] for rid in rids]
            ttfts = [q.t_first_token - q.t_submit for q in fin
                     if q.t_first_token]
            n_tok = sum(len(q.new_tokens) for q in fin)
            return eng, [tuple(q.new_tokens) for q in fin], {
                "ttft_p50_ms": round(float(np.percentile(ttfts, 50))
                                     * 1e3, 3),
                "ttft_p99_ms": round(float(np.percentile(ttfts, 99))
                                     * 1e3, 3),
                "tokens_per_sec": round(n_tok / dt, 2),
                "recompiles_after_warmup": eng.stats.compiles - warm,
                "rounds": rnd,
            }

        eng_on, out_on, on = serve(prefix=True, spec=False)
        eng_off, out_off, off = serve(prefix=False, spec=False)
        eng_sp, out_sp, sp = serve(prefix=True, spec=True)
        # the compile gate: neither the cache (block-table surgery on
        # the host) nor spec decode (fixed propose/verify shapes) may
        # add a post-warmup program in ANY mode
        assert on["recompiles_after_warmup"] == 0, on
        assert off["recompiles_after_warmup"] == 0, off
        assert sp["recompiles_after_warmup"] == 0, sp

        pfx = eng_on.prefix_cache_stats()
        hit_rate = pfx["hits"] / max(pfx["lookups"], 1)
        # ledger-exact accounting: every prompt token was either FED
        # through a prefill chunk or SKIPPED via a cache hit — the two
        # ledgers must partition the trace exactly (warmup excluded:
        # stats are read before the measured phase only for fed/skip
        # deltas; here both ledgers include warmup's fed tokens, so
        # add them to the closed form)
        warm_tok = (sys_pages * page + tail_lo) + (page - 2)
        ledger_exact = (pfx["fed_tokens"] + pfx["skipped_tokens"]
                        == total_prompt_tok + warm_tok)
        _emit({
            "metric": "serving_prefix_ttft_p50_ms",
            "value": on["ttft_p50_ms"],
            "unit": "ms",
            # the gate: mapping cached pages must cut time-to-first-
            # token vs re-prefilling the shared prefix every arrival
            "vs_baseline": round(off["ttft_p50_ms"]
                                 / max(on["ttft_p50_ms"], 1e-9), 4),
            "prefix_on": on, "prefix_off": off,
            "cache_hit_rate": round(hit_rate, 4),
            "skipped_tokens": pfx["skipped_tokens"],
            "fed_tokens": pfx["fed_tokens"],
            "ledger_exact": bool(ledger_exact),
            "cow_copies": pfx["cow"], "pages_reclaimed": pfx["reclaimed"],
            "system_prompts": n_sys, "users": n_users,
            "prefix_pages": sys_pages, "page_size": page,
            "prefill_chunk": Sc, "batch": B,
            "telemetry": _telemetry_section(),
            "device": str(getattr(dev, "device_kind", dev.platform)),
        })
        _emit({
            "metric": "serving_prefix_cache_hit_rate",
            "value": round(hit_rate, 4), "unit": "ratio",
            # acceptance floor from the trace construction: with 3
            # system prompts over 18+ users, most lookups must hit
            "vs_baseline": round(hit_rate / 0.5, 4),
            "hits": pfx["hits"], "lookups": pfx["lookups"],
            "ledger_exact": bool(ledger_exact)})

        spec = eng_sp.spec_stats()
        _emit({
            "metric": "serving_spec_tokens_per_step",
            "value": round(spec["tokens_per_step"], 4),
            "unit": "tokens/step",
            # plain decode commits exactly 1 token per row-step; the
            # draft-verify lattice must beat that at its acceptance
            "vs_baseline": round(spec["tokens_per_step"], 4),
            "accept_rate": round(spec["accept_rate"], 4),
            "proposed": spec["proposed"], "accepted": spec["accepted"],
            "spec_tokens": k, "draft": "self (acceptance ceiling)",
            "spec_run": sp})

        # the exactness gate (bench_compare _EXACT): greedy spec decode
        # and prefix-cache sharing are both REORDERINGS of the same
        # computation, so all three serves of the same trace must emit
        # identical token streams, with the fed+skipped ledger closed
        ok = (out_on == out_off == out_sp) and ledger_exact \
            and hit_rate > 0.5
        _emit({"metric": "serving_prefix_spec_parity",
               "value": 1.0 if ok else 0.0, "unit": "pass",
               "vs_baseline": 1.0 if ok else 0.0,
               "outputs_equal": bool(out_on == out_off == out_sp),
               "ledger_exact": bool(ledger_exact),
               "hit_rate_gt_half": bool(hit_rate > 0.5)})
    finally:
        paddle.set_default_dtype(old_dtype)


# ---------------------------------------------------------------------------
# Disaggregated prefill/decode serving (ISSUE 20): a phase-split fleet
# (1 prefill replica streaming KV pages to 1 decode replica through
# inference/disagg.py, fronted by the inference/router.py front door)
# vs a unified 2-replica fleet on the SAME bursty Poisson trace.
# Same chip count on both sides, so goodput-per-chip is the headline;
# the exactness gates (bench_compare _EXACT): bit-identical token
# streams, migration wire bytes pinned to the pages x page_bytes +
# block-table-row closed form, zero post-warmup recompiles on BOTH
# replica kinds.
# ---------------------------------------------------------------------------
def bench_serving_disagg(on_tpu, dev):
    import paddle_tpu as paddle
    from paddle_tpu.inference import Config, Router, ServingEngine, \
        create_predictor
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_7b)

    old_dtype = paddle.get_default_dtype()
    if on_tpu:
        paddle.set_default_dtype("bfloat16")
        cfg = llama_7b(max_position_embeddings=1024, dtype="bfloat16")
        page, B, Sc = 128, 8, 256
        n_req, len_lo, len_hi, n_new, rate = 24, 128, 448, 48, 1.0
        pool = None                  # geometric default
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          intermediate_size=128,
                          max_position_embeddings=256)
        page, B, Sc = 8, 4, 16
        n_req, len_lo, len_hi, n_new, rate = 14, 5, 30, 8, 0.8
        pool = 32
    try:
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        conf = Config().set_model(model).enable_paged_kv(page_size=page)
        if on_tpu:
            conf.enable_weight_only("weight_only_int8")
        r = np.random.RandomState(20)
        # bursty Poisson arrivals on the router's step clock
        gaps = r.exponential(1.0 / rate, n_req)
        trace = [(float(t),
                  r.randint(1, cfg.vocab_size,
                            (int(r.randint(len_lo, len_hi)),)))
                 for t in np.cumsum(gaps)]

        def mk(phase=None):
            return ServingEngine(create_predictor(conf), max_batch=B,
                                 prefill_chunk=Sc, pool_pages=pool,
                                 phase=phase)

        def serve(disagg):
            if disagg:
                rt = Router([("prefill0", mk("prefill")),
                             ("decode0", mk("decode"))])
            else:
                rt = Router([("u0", mk()), ("u1", mk())])
            engs = [rep.engine for rep in rt.replicas]
            # warmup: one request PER FRONTDOOR REPLICA through every
            # program shape (prefill chunks, fused decode, page
            # read/write on the migration path) — least-loaded
            # placement spreads sequential submissions across the pool
            for _ in range(len(rt.frontdoor)):
                rt.submit(r.randint(1, cfg.vocab_size, (len_hi,)),
                          max_new_tokens=3)
            rt.run()
            warm = sum(e.stats.compiles for e in engs)
            gids, i, rnd = [], 0, 0
            t0 = time.perf_counter()
            while i < len(trace) or rt.pending:
                while i < len(trace) and trace[i][0] <= rnd:
                    gids.append(rt.submit(trace[i][1],
                                          max_new_tokens=n_new))
                    i += 1
                rt.step()
                rnd += 1
            dt = max(time.perf_counter() - t0, 1e-4)
            fin = [rt.result(g) for g in gids]
            ttfts = [q.t_first_token - q.t_submit for q in fin
                     if q.t_first_token]
            tpots = [(q.t_finish - q.t_first_token)
                     / (len(q.new_tokens) - 1) for q in fin
                     if q.t_first_token and len(q.new_tokens) > 1]
            n_tok = sum(len(q.new_tokens) for q in fin)
            return rt, [tuple(q.new_tokens) for q in fin], {
                "ttft_p99_ms": round(float(np.percentile(ttfts, 99))
                                     * 1e3, 3),
                "tpot_p99_ms": round(float(np.percentile(tpots, 99))
                                     * 1e3, 3),
                "goodput_tokens_per_sec_per_chip":
                    round(n_tok / dt / len(engs), 2),
                "recompiles_after_warmup":
                    sum(e.stats.compiles for e in engs) - warm,
                "rounds": rnd,
            }

        rt_d, out_d, dis = serve(disagg=True)
        rt_u, out_u, uni = serve(disagg=False)
        # the compile gate: a warmed fleet must serve the whole trace
        # (migrations included) without a single new XLA program
        assert dis["recompiles_after_warmup"] == 0, dis
        assert uni["recompiles_after_warmup"] == 0, uni

        # migration byte accounting: measured wire bytes (also booked
        # on the comm ledger's migrate axis and the migration_bytes
        # counter) == the closed form over the served requests,
        # warmup included
        peng = rt_d.replicas[0].engine
        mcfg = model.config
        page_bytes = (2 * mcfg.num_layers * mcfg.num_kv_heads * page
                      * mcfg.head_dim * np.dtype(peng._dtype).itemsize)
        lens = [len(p) for _, p in trace] \
            + [len_hi] * len(rt_d.frontdoor)
        closed = sum((-(-L // page)) * page_bytes + peng.npages * 4
                     for L in lens)
        bytes_exact = rt_d.migrator.wire_bytes == closed
        parity = out_d == out_u

        _emit({
            "metric": "serving_disagg_ttft_p99_ms",
            "value": dis["ttft_p99_ms"], "unit": "ms",
            # chunked prefill at full MFU with decode offloaded: the
            # tail TTFT must not regress vs the co-located fleet
            "vs_baseline": round(uni["ttft_p99_ms"]
                                 / max(dis["ttft_p99_ms"], 1e-9), 4),
            "disagg": dis, "unified": uni,
            "requests": n_req, "page_size": page, "prefill_chunk": Sc,
            "batch": B,
            "telemetry": _telemetry_section(),
            "device": str(getattr(dev, "device_kind", dev.platform)),
        })
        _emit({
            "metric": "serving_disagg_tpot_p99_ms",
            "value": dis["tpot_p99_ms"], "unit": "ms",
            # the disagg pitch: decode rows never stall behind prefill
            # chunks, so the inter-token tail tightens
            "vs_baseline": round(uni["tpot_p99_ms"]
                                 / max(dis["tpot_p99_ms"], 1e-9), 4),
            "disagg": dis, "unified": uni})
        _emit({
            "metric": "serving_disagg_goodput_per_chip",
            "value": dis["goodput_tokens_per_sec_per_chip"],
            "unit": "tokens/s/chip",
            "vs_baseline": round(
                dis["goodput_tokens_per_sec_per_chip"]
                / max(uni["goodput_tokens_per_sec_per_chip"], 1e-9),
                4),
            "disagg": dis, "unified": uni})
        _emit({
            "metric": "serving_disagg_parity",
            "value": 1.0 if parity else 0.0, "unit": "pass",
            "vs_baseline": 1.0 if parity else 0.0,
            "outputs_equal": bool(parity),
            "migrated": rt_d.migrator.migrated})
        _emit({
            "metric": "serving_disagg_migration_bytes",
            "value": 1.0 if bytes_exact else 0.0, "unit": "pass",
            "vs_baseline": 1.0 if bytes_exact else 0.0,
            "wire_bytes": int(rt_d.migrator.wire_bytes),
            "closed_form": int(closed),
            "page_bytes": int(page_bytes),
            "block_table_row_bytes": int(peng.npages * 4)})
    finally:
        paddle.set_default_dtype(old_dtype)


# ---------------------------------------------------------------------------
# 3. GPT-13B hybrid TP x PP x DP + GroupSharded stage2 (BASELINE row 3).
# Needs >= 8 chips; on one chip it reports the requirement cleanly, and
# on the CPU harness it runs the FULL hybrid code path on tiny shapes
# (correctness: the same strategy dryrun_multichip validates).
# ---------------------------------------------------------------------------
def bench_gpt13b_hybrid(on_tpu, dev):
    import os
    import shutil
    import tempfile

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig

    from paddle_tpu.observability import flops as _flops
    from paddle_tpu.observability import goodput as _gp
    from paddle_tpu.observability import memledger as _ml
    from paddle_tpu.observability import timeseries as _ts

    # HBM memory ledger on for every engine this bench builds (the
    # engines live behind fleet.distributed_model, so the env knob is
    # the plumbing): one extra AOT analysis per program, zero
    # recompiles of the live step (the recompiles_after_warmup field
    # below still gates at 0 with the ledger on)
    os.environ["PADDLE_TPU_MEM_LEDGER"] = "1"

    n = jax.device_count()
    if on_tpu and n < 8:
        _emit({"metric": "gpt13b_hybrid_train_tokens_per_sec",
               "value": 0.0, "unit": "needs_chips", "vs_baseline": 0.0,
               "needs_devices": 8, "have_devices": n,
               "note": "13B = TP4 x PP2 x sharding(n/8) stage2; "
                       "config compiled/validated on the 8-virtual-"
                       "device CPU mesh (dryrun + this bench on CPU)"})
        return
    if on_tpu:
        # GPT-13B: hidden 5120 x 40 layers x 40 heads (BASELINE row 3)
        cfg = GPTConfig(vocab_size=50304, hidden_size=5120,
                        num_layers=40, num_heads=40,
                        max_position_embeddings=1024, dtype="bfloat16")
        mp_deg, shard_deg = 4, max(n // 8, 1)
        B, S, steps, state_dtype = 4 * shard_deg, 1024, 5, "bfloat16"
        buf_mb = 64.0
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                        num_heads=4, max_position_embeddings=64)
        # the smoke mesh carries a REAL sharding axis (mp2 x pp2 x
        # sharding2 = 8 vdevs) so the stage-2 grad reduce-scatter —
        # the tail comm_overlap exists to hide — is actually on the
        # wire and in the exposed-comm report
        mp_deg, shard_deg = 2, 2
        B, S, steps, state_dtype = 2 * shard_deg * 2, 16, 2, None
        buf_mb = 0.001        # tiny target -> several buckets at toy size

    # five lines, one knob apart each: vpp=1 (GPipe-family rotation),
    # vpp=2 (circular interleave), vpp=1 + comm_overlap (T3-style
    # bucketed backward: per-bucket grad reduce-scatter inside the
    # backward seam, distributed/grad_buckets.py), overlap +
    # quant_comm (int8 error-feedback quantized collectives,
    # distributed/quant_comm.py — the quant-vs-overlap pair isolates
    # the wire compression), and overlap + sharding_stage=3 (ZeRO-3
    # shard-only parameter storage with the bucketed just-in-time
    # gather — the stage3-vs-overlap pair isolates the storage
    # discipline: same grads, params stored at 1/sharding_degree and
    # re-gathered per signature bucket at forward entry). base vs
    # overlap is the same program shape, so the loss-parity and
    # profile_exposed_comm("sharding") comparison is one flag apart.
    quant_chunk = 256 if on_tpu else 64
    gp_base = tempfile.mkdtemp(prefix="goodput_gpt13b_")
    results = {}
    for tag, vpp, overlap, quant, stage, offload in (
            ("base", 1, False, False, 2, None),
            ("vpp2", 2, False, False, 2, None),
            ("overlap", 1, True, False, 2, None),
            ("quant", 1, True, True, 2, None),
            ("stage3", 1, True, False, 3, None),
            # the host tier rides the stage-3 line one knob apart:
            # optimizer state host-resident between steps, prefetched
            # per-bucket just in time (distributed/host_offload.py)
            ("offload", 1, True, False, 3,
             {"optimizer": True, "prefetch_buckets": 2})):
        # one goodput journal per tag (run-level wall attribution:
        # compile vs step_compute vs idle; observability/goodput.py)
        # plus the durable metrics journal beside it (observability/
        # timeseries): both are host-side file IO on fetched scalars,
        # so the recompiles_after_warmup gate below must hold at 0
        # with the sampler attached for the whole measured window
        gp_led = _gp.attach_dir(os.path.join(gp_base, tag))
        ts_smp = _ts.attach_dir(os.path.join(gp_base, tag),
                                interval_s=0.5)
        paddle.seed(0)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "mp_degree": mp_deg,
            "pp_degree": 2,
            "sharding_degree": shard_deg,
            # collective-matmul overlap on the TP hot
            # path (distributed/collective_matmul.py)
            "mp_configs": {"mp_async_allreduce": True},
            "pp_configs": {"num_virtual_pipeline_stages": vpp},
            # T3-style bucketed grad sync (grad_buckets.py) + the ZeRO
            # stage knob (3 = shard-only params, just-in-time gather)
            "sharding_configs": {"comm_overlap": overlap,
                                 "comm_buffer_size_MB": buf_mb,
                                 "sharding_stage": stage,
                                 "offload": offload},
            # int8 quantized collectives with error feedback
            # (quant_comm.py): grad reduce-scatter buckets, TP rings +
            # activation allreduces, and the ZeRO param gather
            "quant_comm": {"dtype": "int8" if quant else "none",
                           "chunk": quant_chunk,
                           "error_feedback": True}}
        strategy.sharding_configs = {"stage": stage}
        strategy.pipeline_configs = {
            "accumulate_steps": 2,
            "micro_batch_size": B // (2 * shard_deg)}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        model = GPTForCausalLMPipe(cfg)
        dist_model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(
            paddle.optimizer.AdamW(learning_rate=1e-4,
                                   parameters=model.parameters(),
                                   state_dtype=state_dtype))
        r = np.random.RandomState(0)
        ids = r.randint(0, cfg.vocab_size, (B, S + 1))
        x = paddle.to_tensor(ids[:, :-1])
        y = paddle.to_tensor(ids[:, 1:])
        losses = [float(dist_model.train_batch([x, y], opt))]
        stats = dist_model._engine.stats
        compiles_warm = stats.compiles
        # host-offload steady state: cumulative transfer-ledger bytes
        # around the timed window pin the per-step cost exactly (one
        # h2d prefetch + one d2h page-out of every offloaded slot)
        tier = dist_model._engine._offload
        off_t0 = tier.transfer_bytes() if tier is not None else 0
        t0 = time.perf_counter()
        for _ in range(steps):
            losses.append(float(dist_model.train_batch([x, y], opt)))
        dt = time.perf_counter() - t0
        off_steady = (tier.transfer_bytes() - off_t0) \
            if tier is not None else 0
        tok_s = B * S * steps / dt
        # goodput summary BEFORE the offline exposed-comm replays (the
        # profiler suppresses goodput segments, so its wall time would
        # book as idle and dilute the percentage)
        gp_summary = gp_led.summary()
        # close the tag's metrics journal with one guaranteed final
        # sample and pin the per-sample overhead (snapshot + one
        # flushed JSONL line — bounded host cost, never device work)
        ts_smp.sample_now()
        ts_stats = ts_smp.stats()
        ts_smp.close()
        assert ts_stats["samples"] >= 1, ts_stats
        assert (ts_stats["overhead_seconds"]
                <= 0.25 * ts_stats["samples"]), ts_stats
        # exposed-comm attribution (observability/commledger): per-axis
        # overlapped-vs-exposed split + grad_sync_exposed_seconds. The
        # gauges land in the telemetry section below; the compact
        # report rides on the line itself. Offline pass — state is
        # restored and the compile counters above are not perturbed.
        prof = dist_model.profile_exposed_comm([x, y], repeats=2)
        exposed_comm = {
            "step_seconds": round(prof.step_seconds, 6),
            "exposed_seconds": {a: round(v, 6) for a, v in
                                prof.exposed_seconds.items()},
            "replay_seconds": {a: round(v, 6) for a, v in
                               prof.replay_seconds.items()},
            "exposed_fraction": {a: round(v, 4) for a, v in
                                 prof.exposed_fraction.items()},
            "grad_sync_exposed_seconds": round(
                prof.grad_sync_exposed_seconds, 6),
        }
        eng = dist_model._engine
        led = eng.comm_ledger()
        comm_bytes_per_step = {
            f"{a}/{o}": round(t["bytes"], 1)
            for (a, o), t in sorted(led.totals().items())} if led else {}
        plan = eng._bucket_plan
        # memory ledger + state accounting + roofline verdict: the
        # per-executable byte classes (XLA memory_analysis), the
        # measured model-state breakdown with the auto_tuner drift,
        # and the compute/HBM/ICI bound verdict joining flops + comm +
        # memory (observability/memledger.py)
        mem_led = eng.memory_ledger()
        acct = eng.state_accounting()
        roof = eng.roofline_report(exposed=prof)
        results[tag] = {"losses": losses, "prof": prof, "led": led,
                        "plan": plan, "eng": eng, "acct": acct,
                        "roof": roof, "goodput": gp_summary,
                        "off_steady": off_steady,
                        "ts_stats": ts_stats,
                        "recompiles": stats.compiles - compiles_warm}
        peak, _ = _chip(dev)
        n_params = cfg.num_params()
        mfu = (6.0 * n_params * tok_s / (peak * n)) if peak else 0.0
        base = ("gpt13b_hybrid_train_tokens_per_sec" if on_tpu
                else "gpt13b_hybrid_smoke_tokens_per_sec")
        line = {
            "metric": base if tag == "base" else
            base.replace("gpt13b_hybrid", f"gpt13b_hybrid_{tag}"),
            "value": round(tok_s, 2),
            "unit": "tokens/s",
            "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
            "mfu": round(mfu, 4) if peak else 0.0,
            "mesh": f"sharding{shard_deg}xpp2xmp{mp_deg}", "devices": n,
            "mp_async_allreduce": True,
            "pp_vpp": vpp,
            "comm_overlap": overlap,
            "quant_comm": quant,
            "sharding_stage": stage,
            "comm_bytes_total": round(led.bytes_for(), 1) if led
            else 0.0,
            # engine compile-cache counters: steady state must be
            # recompile-free (overlap regressions keyed on traced shapes
            # would show here)
            "compiles": stats.compiles,
            "cache_hits": stats.cache_hits,
            "recompiles_after_warmup": stats.compiles - compiles_warm,
            # static comm ledger of the compiled step (bytes-on-wire
            # per participant per step, by axis/op) + the exposed-comm
            # attribution — the instrument panel quant_comm / T3
            # overlap / MoE a2a report through
            "comm_bytes_per_step": comm_bytes_per_step,
            "exposed_comm": exposed_comm,
            "memory": {
                "executable": mem_led.to_dict() if mem_led else {},
                "state": acct.to_dict(),
            },
            "roofline": roof.to_dict(),
            # run-level wall-clock attribution of THIS tag's run
            # (tools/run_report.py draws the waterfall;
            # tools/step_report.py columns + --strict gate ride on it)
            "goodput": gp_summary,
            # the durable metrics journal the same run wrote next to
            # the goodput ledger (tools/fleet_report.py reads these)
            "timeseries": {
                "samples": ts_stats["samples"],
                "journal_bytes": ts_stats["journal_bytes"],
                "overhead_seconds": round(
                    ts_stats["overhead_seconds"], 6)},
            "telemetry": _telemetry_section(),
            "device": str(getattr(dev, "device_kind", dev.platform)),
        }
        if overlap and plan is not None:
            summ = plan.summary()
            line["grad_buckets"] = summ["buckets"]
            line["bucket_payload_bytes"] = summ["bucket_payload_bytes"]
            line["grad_sync_floor_seconds"] = round(
                _flops.comm_seconds_lower_bound(
                    led.bytes_for(axis="sharding"), dev), 6) if led \
                else 0.0
        if tier is not None:
            line["offload"] = {
                "host_resident_bytes": tier.host_resident_bytes(),
                "transfer_bytes_d2h": tier.transfer_bytes(
                    direction="d2h"),
                "transfer_bytes_h2d": tier.transfer_bytes(
                    direction="h2d"),
                "steady_bytes_per_step": off_steady // max(steps, 1),
                "prefetch_seconds": round(tier._last_prefetch_s, 6),
            }
        if quant and led is not None:
            # realized per-axis wire compression (int8 payload + bf16
            # scale sidecars vs the uncompressed-equivalent bytes)
            line["quant_ratios"] = {a: round(v, 4) for a, v
                                    in led.quant_ratios().items()}
            line["quant_residual_buffers"] = len(eng._quant_residuals)
        _emit(line)

    # the T3 acceptance pair: knob-on vs knob-off on the same program —
    # loss parity (exact-gated in tools/bench_compare.py) and the
    # sharding axis's exposed seconds (direction-aware: lower is better)
    base_r, ov_r = results["base"], results["overlap"]
    parity = max(abs(a - b) for a, b in zip(base_r["losses"],
                                            ov_r["losses"]))
    _emit({"metric": "gpt13b_hybrid_overlap_loss_parity",
           "value": 1.0 if parity <= 1e-5 else 0.0, "unit": "pass",
           "vs_baseline": 1.0, "max_abs_loss_diff": parity,
           "grad_buckets": (ov_r["plan"].num_buckets
                            if ov_r["plan"] else 0)})
    exp_off = base_r["prof"].exposed_seconds.get("sharding", 0.0)
    exp_on = ov_r["prof"].exposed_seconds.get("sharding", 0.0)
    _emit({"metric": "gpt13b_hybrid_grad_sync_exposed_seconds",
           "value": round(exp_on, 6), "unit": "s", "vs_baseline": 0.0,
           "knob_off_exposed_seconds": round(exp_off, 6),
           "exposed_lower_than_knob_off": bool(exp_on < exp_off),
           "note": "CPU smoke proves parity + compile stability; the "
                   "realized overlap win is an on-TPU ROADMAP item"})
    # the quant_comm acceptance pair: quant vs overlap on the same
    # program — total comm-ledger wire bytes must drop to <= 0.30x
    # (int8 payload + bf16 scales closed forms; lower-better in
    # tools/bench_compare.py) and the deterministic-horizon loss gap
    # stays loose-bounded (the REAL convergence gate is the 200-step
    # parity test in tests/test_quant_comm.py — this line just tracks
    # drift on the flagship config)
    q_r = results["quant"]
    q_bytes = q_r["led"].bytes_for() if q_r["led"] else 0.0
    o_bytes = ov_r["led"].bytes_for() if ov_r["led"] else 0.0
    wire_ratio = (q_bytes / o_bytes) if o_bytes else 0.0
    _emit({"metric": "gpt13b_hybrid_quant_wire_ratio",
           "value": round(wire_ratio, 4), "unit": "x",
           "vs_baseline": 0.0,
           "quant_bytes_per_step": round(q_bytes, 1),
           "fp32_bytes_per_step": round(o_bytes, 1),
           "quant_ratios": {a: round(v, 4) for a, v in
                            (q_r["led"].quant_ratios().items()
                             if q_r["led"] else ())},
           "le_030": bool(wire_ratio <= 0.30)})
    q_gap = max(abs(a - b) for a, b in zip(ov_r["losses"],
                                           q_r["losses"]))
    _emit({"metric": "gpt13b_hybrid_quant_loss_gap",
           "value": round(q_gap, 6), "unit": "abs", "vs_baseline": 0.0,
           "losses_quant": [round(v, 5) for v in q_r["losses"]],
           "losses_fp32": [round(v, 5) for v in ov_r["losses"]]})
    # the ZeRO stage-3 acceptance pair: stage3 vs overlap on the same
    # program shape, one knob apart — loss parity (exact-gated in
    # tools/bench_compare.py: the gather is pure data movement, so
    # stage 3 must land bit-on the stage-2 trajectory) plus the
    # just-in-time gather's wire bytes pinned to the (p-1) x shard
    # closed form (scan_trips-exact on the stacked seam)
    s3_r = results["stage3"]
    s3_parity = max(abs(a - b) for a, b in zip(ov_r["losses"],
                                               s3_r["losses"]))
    s3_eng = s3_r["eng"]
    covered_shard_bytes = sum(
        _ml.shard_bytes(p._value) for p in s3_eng.trainable
        if s3_eng._zero.entry(p) is not None
        and s3_eng._zero.entry(p)[1])
    gather_closed = (shard_deg - 1) * covered_shard_bytes
    gather_bytes = (s3_r["led"].bytes_for(axis="sharding",
                                          op="all_gather")
                    if s3_r["led"] else 0.0)
    _emit({"metric": "gpt13b_hybrid_stage3_loss_parity",
           "value": 1.0 if (s3_parity <= 1e-5
                            and gather_bytes == gather_closed) else 0.0,
           "unit": "pass", "vs_baseline": 1.0,
           "max_abs_loss_diff": s3_parity,
           "gather_bytes_per_step": round(gather_bytes, 1),
           "gather_bytes_closed_form": round(float(gather_closed), 1),
           "gather_ops_per_step": (s3_r["led"].ops_for(
               axis="sharding", op="all_gather") if s3_r["led"] else 0)})
    # stage-3 memory exact gate: measured state accounting == closed
    # form byte-for-byte AND the params component sits at exactly
    # 1/sharding_degree of the stage-2 (replicated-storage) image —
    # the unlock that lets models outgrow one chip's HBM
    s3_acct = s3_r["acct"]
    s3_closed = _ml.closed_form_state_bytes(s3_eng)
    ov_params = results["overlap"]["acct"].components.get("params", 0)
    s3_params = s3_acct.components.get("params", 0)
    uncovered = sum(
        _ml.shard_bytes(p._value) for p in s3_eng.params
        if not (s3_eng._zero.entry(p) is not None
                and s3_eng._zero.entry(p)[1]))
    s3_ok = (all(s3_acct.components.get(k) == v
                 for k, v in s3_closed.items())
             and (s3_params - uncovered) * shard_deg
             == ov_params - uncovered)
    _emit({"metric": "gpt13b_hybrid_stage3_mem_state_parity",
           "value": 1.0 if s3_ok else 0.0, "unit": "pass",
           "vs_baseline": 1.0 if s3_ok else 0.0,
           "measured": {k: s3_acct.components.get(k) for k in s3_closed},
           "closed_form": s3_closed,
           "params_bytes_stage3": s3_params,
           "params_bytes_stage2": ov_params,
           "sharding_degree": shard_deg,
           "analytic_drift": round(s3_acct.drift, 4)})
    # the host-offload acceptance pair: offload vs stage3, one knob
    # apart — the tier is pure data movement (bytes copied, never
    # re-derived, outside the compiled step), so the loss trajectory
    # must land BIT-exactly on stage 3's with zero recompiles, and the
    # cumulative transfer ledger must pin to the closed form: every
    # offloaded slot's per-device shard bytes once per direction per
    # step (the steady-state window), with conservation d2h - h2d ==
    # bytes currently host-resident (exact-gated in bench_compare)
    from paddle_tpu.distributed import host_offload as _ho
    off_r = results["offload"]
    off_parity = max(abs(a - b) for a, b in zip(s3_r["losses"],
                                                off_r["losses"]))
    off_eng = off_r["eng"]
    tier = off_eng._offload
    slot_closed = sum(
        _ho.host_shard_bytes(tier._get(off_eng, key))
        for key, _c, _b in tier._iter_slots(off_eng))
    resident = tier.host_resident_bytes()
    conserved = (tier.transfer_bytes(direction="d2h")
                 - tier.transfer_bytes(direction="h2d"))
    steady_ok = off_r["off_steady"] == 2 * steps * slot_closed
    off_recompiles = off_r["recompiles"]
    _emit({"metric": "gpt13b_hybrid_offload_loss_parity",
           "value": 1.0 if (off_parity == 0.0 and resident == slot_closed
                            and conserved == resident and steady_ok
                            and off_recompiles == 0) else 0.0,
           "unit": "pass", "vs_baseline": 1.0,
           "max_abs_loss_diff": off_parity,
           "host_resident_bytes": resident,
           "host_resident_closed_form": slot_closed,
           "transfer_conservation_bytes": conserved,
           "steady_bytes_per_step": off_r["off_steady"] // max(steps, 1),
           "steady_closed_form_per_step": 2 * slot_closed,
           "recompiles_after_warmup": off_recompiles})
    # offload memory exact gate: the measured accounting (between
    # steps, i.e. with the tier paged OUT) books the offloaded slots
    # under host_state == the closed form, and the DEVICE-resident
    # image drops below stage 3's by exactly that amount
    off_acct = off_r["acct"]
    off_closed = _ml.closed_form_state_bytes(off_eng)
    s3_dev = s3_r["acct"].device_bytes
    off_ok = (all(off_acct.components.get(k) == v
                  for k, v in off_closed.items())
              and off_acct.components.get("host_state", 0) > 0
              and off_acct.device_bytes
              == s3_dev - off_acct.components.get("host_state", 0))
    _emit({"metric": "gpt13b_hybrid_offload_mem_state_parity",
           "value": 1.0 if off_ok else 0.0, "unit": "pass",
           "vs_baseline": 1.0 if off_ok else 0.0,
           "measured": {k: off_acct.components.get(k)
                        for k in off_closed},
           "closed_form": off_closed,
           "device_bytes_offload": off_acct.device_bytes,
           "device_bytes_stage3": s3_dev,
           "analytic_drift": round(off_acct.drift, 4)})
    # the capability line: the 13B flagship on its OWN 8-chip slice
    # (TP4 x PP2; sharding_degree = n // 8 = 1, so the fp32 optimizer
    # image has no axis left to shard away) priced by the auto_tuner
    # cost model — a 16 GB chip cannot hold it, and the SAME config
    # with the optimizer tier offloaded fits: the tier is the axis
    # past the last on-chip scale knob
    from paddle_tpu.distributed.auto_tuner.cost_model import (
        estimate_memory_gb)
    model_13b = {"hidden_size": 5120, "num_layers": 40,
                 "vocab_size": 50304}
    cfg_13b = {"dp_degree": 1, "mp_degree": 4, "pp_degree": 2,
               "sharding_degree": 1, "sharding_stage": 3,
               "micro_batch_size": 1}
    hbm_gb = 16.0
    m_s3 = estimate_memory_gb(model_13b, cfg_13b, global_batch=8,
                              seq_len=1024, recompute=True)
    m_off = estimate_memory_gb(
        model_13b, dict(cfg_13b, offload={"optimizer": True,
                                          "prefetch_buckets": 2}),
        global_batch=8, seq_len=1024, recompute=True)
    _emit({"metric": "gpt13b_hybrid_offload_overhbm_trainable",
           "value": 1.0 if (m_s3 > hbm_gb >= m_off) else 0.0,
           "unit": "pass", "vs_baseline": 1.0,
           "hbm_gb": hbm_gb,
           "stage3_image_gb": round(m_s3, 2),
           "offload_image_gb": round(m_off, 2)})
    # memory-ledger exact gate: the measured state accounting (shard_
    # shape path) must equal the closed form (global shape / sharding
    # degree path) byte-for-byte — incl. ZeRO stage-2 scattered state
    # and the pp x vpp stacked-chunk ownership (bench_compare _EXACT)
    acct = base_r["acct"]
    closed = _ml.closed_form_state_bytes(base_r["eng"])
    ok = all(acct.components.get(k) == v for k, v in closed.items())
    _emit({"metric": "gpt13b_hybrid_mem_state_parity",
           "value": 1.0 if ok else 0.0, "unit": "pass",
           "vs_baseline": 1.0 if ok else 0.0,
           "measured": {k: acct.components.get(k) for k in closed},
           "closed_form": closed,
           "analytic_drift": round(acct.drift, 4)})
    # HBM headroom of the roofline verdict (direction-aware in
    # bench_compare: higher = more slack before the memory wall; 0 on
    # CPU where peak tables are unknown and the verdict is "unknown")
    roof = base_r["roof"]
    _emit({"metric": "gpt13b_hybrid_hbm_headroom_pct",
           "value": round(roof.headroom_pct.get("hbm", 0.0), 2),
           "unit": "pct", "vs_baseline": 0.0, "bound": roof.bound,
           "roofline_seconds": {k: round(v, 6)
                                for k, v in roof.seconds.items()}})
    # run-level goodput headline (higher-better in bench_compare; the
    # CPU smoke number is dominated by compile at this toy scale — the
    # trajectory, not the absolute, is the signal) + the health
    # monitor's event count, which must be EXACTLY 0 on this
    # deterministic line (bench_compare _EXACT)
    gp = base_r["goodput"]
    _emit({"metric": "gpt13b_hybrid_goodput_pct",
           "value": gp["goodput_pct"], "unit": "pct",
           "vs_baseline": 0.0,
           "segment_pct": gp["segment_pct"],
           "wall_seconds": gp["wall_seconds"]})
    # sampler cost headline (lower-better in bench_compare): total
    # wall seconds the metrics-journal sampler spent across every tag
    # of this bench — the observability tax must stay near zero
    ts_total = sum(r["ts_stats"]["overhead_seconds"]
                   for r in results.values())
    ts_samples = sum(r["ts_stats"]["samples"] for r in results.values())
    _emit({"metric": "gpt13b_hybrid_sampler_overhead_seconds",
           "value": round(ts_total, 6), "unit": "s", "vs_baseline": 0.0,
           "samples": ts_samples,
           "journal_bytes": sum(r["ts_stats"]["journal_bytes"]
                                for r in results.values()),
           "seconds_per_sample": round(ts_total / max(ts_samples, 1),
                                       6)})
    # each tag's engine carries its OWN health monitor (per-run
    # windows); a deterministic fixed-seed bench must raise no event
    # on any of them
    n_events = sum(r["eng"]._health.event_count()
                   for r in results.values())
    _emit({"metric": "gpt13b_hybrid_health_spike_events",
           "value": float(n_events),
           "unit": "events", "vs_baseline": 0.0,
           "events": [e for r in results.values()
                      for e in r["eng"]._health.events()][-4:]})
    _gp.detach()
    _ts.detach()
    shutil.rmtree(gp_base, ignore_errors=True)


# ---------------------------------------------------------------------------
# 4a-bis. Checkpoint-save overlap: how much of a full-state crash-
# consistent checkpoint (params + ZeRO-2 moments + AMP + RNG, atomic
# commit protocol) the ASYNC path hides behind training steps on the
# gpt13b_hybrid smoke mesh (mp2 x pp2 x sharding2). The line's value is
# the async stall (lower better, registered direction-aware in
# tools/bench_compare.py); the acceptance bound rides along as
# async_stall_lt_step (< 1 step-time of stall).
# ---------------------------------------------------------------------------
def bench_ckpt_overlap(on_tpu, dev):
    import os
    import shutil
    import tempfile

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.models import GPTForCausalLMPipe
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.observability import goodput as _gp
    from paddle_tpu.observability.catalog import ckpt_metrics

    n = jax.device_count()
    if on_tpu and n < 8:
        _emit({"metric": "ckpt_save_overlap_stall_seconds",
               "value": 0.0, "unit": "needs_chips", "vs_baseline": 0.0,
               "needs_devices": 8, "have_devices": n})
        return
    # the gpt13b_hybrid smoke topology; on chip a fatter layer so the
    # snapshot/write actually move bytes worth hiding
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024,
                        num_layers=8, num_heads=8,
                        max_position_embeddings=512, dtype="bfloat16")
        B, S = 8, 512
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                        num_heads=4, max_position_embeddings=64)
        B, S = 8, 16
    paddle.seed(0)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
        "sharding_degree": 2}
    strategy.sharding_configs = {"stage": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": B // 4}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    model = GPTForCausalLMPipe(cfg)
    dist_model = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters()))
    r = np.random.RandomState(0)
    ids = r.randint(0, cfg.vocab_size, (B, S + 1))
    x = paddle.to_tensor(ids[:, :-1])
    y = paddle.to_tensor(ids[:, 1:])

    def run_steps(k):
        for _ in range(k):
            float(dist_model.train_batch([x, y], opt))

    # enough step-time behind the save for the write to hide in (the
    # CPU smoke's background writer contends with XLA's host threads,
    # so the window must comfortably exceed the write)
    N = 8
    run_steps(2)                      # warmup (compile)

    def timed(fn, repeats=2):
        """best-of-k: the smoke fights host-load noise, and the BEST
        run is the one where nothing external interfered."""
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            fn()
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    _gp.detach()                 # baseline steps stay unattributed
    dt_base = timed(lambda: run_steps(N))

    base_dir = tempfile.mkdtemp(prefix="ckpt_overlap_")
    try:
        m = ckpt_metrics()
        # sync: the whole commit protocol stalls the step loop
        mgr_s = CheckpointManager(os.path.join(base_dir, "sync"),
                                  keep_last_k=1, async_save=False)
        save_no = [0]

        def sync_round():
            save_no[0] += 1
            dist_model.save_checkpoint(manager=mgr_s, step=save_no[0])
            run_steps(N)

        stall_sync = timed(sync_round) - dt_base
        save_bytes = m["save_bytes"].value()
        snap_s = m["save_seconds"].value(phase="snapshot")
        write_s = m["save_seconds"].value(phase="write")
        # async: only the device->host snapshot stalls; the file
        # protocol runs behind the next N steps (wait() joins the tail
        # that did NOT fit behind them)
        mgr_a = CheckpointManager(os.path.join(base_dir, "async"),
                                  keep_last_k=1, async_save=True)

        def async_round():
            save_no[0] += 1
            dist_model.save_checkpoint(manager=mgr_a, step=save_no[0])
            run_steps(N)
            mgr_a.wait()

        stall_async = timed(async_round) - dt_base
        mgr_a.close()
        # goodput attribution of the two phases: each manager attached
        # its own journal when constructed, so the sync phase's steps +
        # commit stalls landed in <base>/sync and the async phase's —
        # including the writer thread's OVERLAPPED ckpt_async
        # intervals — in <base>/async
        gp_sync = mgr_s._goodput.summary() if mgr_s._goodput else {}
        gp_async = mgr_a._goodput.summary() if mgr_a._goodput else {}
        _gp.detach()
    finally:
        shutil.rmtree(base_dir, ignore_errors=True)

    step_s = dt_base / N
    _emit({
        "metric": "ckpt_save_overlap_stall_seconds",
        "value": round(max(stall_async, 0.0), 6),
        "unit": "s", "vs_baseline": 0.0,
        "sync_stall_seconds": round(max(stall_sync, 0.0), 6),
        "hidden_seconds": round(max(stall_sync - stall_async, 0.0), 6),
        "hidden_fraction": round(
            max(stall_sync - stall_async, 0.0) / stall_sync, 4)
        if stall_sync > 0 else 0.0,
        "step_seconds": round(step_s, 6),
        # the acceptance bound: async save must cost < 1 step-time
        "async_stall_lt_step": bool(stall_async < step_s),
        "save_bytes": save_bytes,
        "snapshot_seconds": round(snap_s, 6),
        "write_seconds": round(write_s, 6),
        "mesh": "sharding2xpp2xmp2", "devices": n,
        "train_steps_behind": N,
        # run-level attribution of the ASYNC phase (the shipping
        # config): ckpt_stall = the snapshot the loop pays, ckpt_async
        # = the overlapped background commit; the sync phase rides
        # along for the contrast (its ckpt_stall carries the whole
        # commit protocol)
        "goodput": gp_async,
        "goodput_sync_phase": gp_sync,
        "telemetry": _telemetry_section(),
        "device": str(getattr(dev, "device_kind", dev.platform)),
    })
    _emit({"metric": "ckpt_overlap_goodput_pct",
           "value": gp_async.get("goodput_pct", 0.0), "unit": "pct",
           "vs_baseline": 0.0,
           "sync_phase_goodput_pct": gp_sync.get("goodput_pct", 0.0),
           "segment_pct": gp_async.get("segment_pct", {})})
    _emit({"metric": "ckpt_overlap_health_spike_events",
           "value": float(dist_model._engine._health.event_count()),
           "unit": "events", "vs_baseline": 0.0})


# ---------------------------------------------------------------------------
# 4b. GPT-MoE hybrid: expert parallelism as a first-class mesh axis.
# TP x EP x DP on 8 vdevs — stacked expert weights sharded over 'ep',
# token dispatch/combine all_to_alls inside the compiled step (fused
# into a ppermute ring behind the expert GEMMs: ep_async_dispatch).
# Gates carried on the line: loss parity <= 1e-5 vs the single-device
# dense-dispatch golden (computed per batch shard so capacity/drop
# decisions match exactly), 0 recompiles after warmup, and the
# expert-load / drop-rate gauges + comm_bytes_total{axis="ep"} in the
# telemetry snapshot.
# ---------------------------------------------------------------------------
def bench_gpt_moe_hybrid(on_tpu, dev):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    n = jax.device_count()
    if n < 8:
        _emit({"metric": "gpt_moe_hybrid_train_tokens_per_sec",
               "value": 0.0, "unit": "needs_chips", "vs_baseline": 0.0,
               "needs_devices": 8, "have_devices": n})
        return
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=12,
                        num_heads=16, max_position_embeddings=1024,
                        dtype="bfloat16", num_experts=16, moe_every=2)
        dp = max(n // 4, 1)
        B, S, steps, state_dtype = 4 * dp, 1024, 5, "bfloat16"
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                        num_heads=4, max_position_embeddings=64,
                        num_experts=8, moe_every=2)
        dp = max(n // 4, 1)
        B, S, steps, state_dtype = 4 * dp, 16, 2, None

    # single-device dense-dispatch golden, built BEFORE fleet.init (no
    # hybrid mesh -> plain layers, MoE group None) from the same seed —
    # the mp/ep model below draws the same full-shape init sequence
    paddle.seed(0)
    golden = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "mp_degree": 2, "ep_degree": 2,
        # dispatch/combine a2a fused into the chunked expert-GEMM ring
        # (distributed/collective_matmul.py moe_a2a_ffn)
        "moe_configs": {"ep_async_dispatch": True}}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 state_dtype=state_dtype)
    eng = ParallelEngine(model, opt, hcg.mesh)

    def loss_fn(m, b):
        return crit(m(b["x"]), b["y"]) + m.aux_loss

    step = eng.train_step(loss_fn)
    r = np.random.RandomState(0)
    ids = r.randint(0, cfg.vocab_size, (B, S + 1))
    x, y = ids[:, :-1], ids[:, 1:]
    batch = {"x": paddle.to_tensor(x), "y": paddle.to_tensor(y)}

    # loss parity on the FIRST step (identical weights): the engine's
    # reported loss is the pmean of per-rank local losses, and each
    # (dp, ep) rank holds one contiguous batch shard — so the golden is
    # the mean of the dense model's loss over the same shards (same
    # per-shard token count -> same capacity buckets -> same drops)
    shards = dp * 2
    Bl = B // shards
    g_losses = []
    for i in range(shards):
        xb = paddle.to_tensor(x[i * Bl:(i + 1) * Bl])
        yb = paddle.to_tensor(y[i * Bl:(i + 1) * Bl])
        g_losses.append(float(loss_fn(golden, {"x": xb, "y": yb})))
    g_loss = float(np.mean(g_losses))
    loss0 = float(step(batch))
    parity_err = abs(loss0 - g_loss)
    parity_tol = 0.02 if on_tpu else 1e-5   # bf16 vs the f32 smoke gate
    compiles_warm = eng.stats.compiles

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(batch)
    float(loss)
    dt = time.perf_counter() - t0
    tok_s = B * S * steps / dt

    led = eng.comm_ledger()
    comm_bytes_per_step = {
        f"{a}/{o}": round(t["bytes"], 1)
        for (a, o), t in sorted(led.totals().items())} if led else {}
    tel = _telemetry_section()
    load = {k.split("expert=")[1].split(",")[0].rstrip("}"): v
            for k, v in tel.items()
            if k.startswith("moe_expert_load") and "layer=layer0" in k}
    peak, _ = _chip(dev)
    n_params = cfg.num_params()
    mfu = (6.0 * n_params * tok_s / (peak * n)) if peak else 0.0
    _emit({
        "metric": "gpt_moe_hybrid_train_tokens_per_sec" if on_tpu
        else "gpt_moe_hybrid_smoke_tokens_per_sec",
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
        "mesh": f"dp{dp}xep2xmp2", "devices": n,
        "num_experts": cfg.num_experts,
        "ep_async_dispatch": True,
        "loss_parity_err": round(parity_err, 8),
        "compiles": eng.stats.compiles,
        "cache_hits": eng.stats.cache_hits,
        "recompiles_after_warmup": eng.stats.compiles - compiles_warm,
        "comm_bytes_per_step": comm_bytes_per_step,
        "expert_load_layer0": load,
        "telemetry": tel,
        "device": str(getattr(dev, "device_kind", dev.platform)),
    })
    # the exact gates ride their own lines so bench_compare can pin them
    _emit({"metric": "gpt_moe_hybrid_loss_parity",
           "value": 1.0 if parity_err <= parity_tol else 0.0,
           "unit": "pass",
           "vs_baseline": 1.0 if parity_err <= parity_tol else 0.0,
           "err": round(parity_err, 8), "tol": parity_tol})


# ---------------------------------------------------------------------------
# 3b. Collective-matmul overlap microbench: the fused ring decompositions
# (distributed/collective_matmul.py — ag_matmul + matmul_rs, the TP/SP
# hot-path pair) vs the unfused all_gather -> GEMM -> psum_scatter chain
# on the same mesh. On TPU the fused rings hide the ICI transfer behind
# partial GEMMs; on the CPU harness the line still emits (correctness +
# plumbing smoke, speedup ~1x is expected there).
# ---------------------------------------------------------------------------
def bench_tp_overlap(on_tpu, dev):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed import collective_matmul as cm
    from paddle_tpu.distributed.engine import _shard_map

    n = jax.device_count()
    if n < 2:
        _emit({"metric": "tp_overlap_matmul_ms", "value": 0.0,
               "unit": "needs_chips", "vs_baseline": 0.0,
               "needs_devices": 2, "have_devices": n})
        return
    mesh = Mesh(np.array(jax.devices()).reshape(n), ("mp",))
    if on_tpu:
        S, B, K, N = 2048, 4, 4096, 4096
        dt, iters = jnp.bfloat16, 20
    else:
        S, B, K, N = 128, 2, 64, 128
        dt, iters = jnp.float32, 3
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(S, B, K), dt)        # seq-major [s, b, h]
    w1 = jnp.asarray(r.randn(K, N), dt)          # column-sharded
    w2 = jnp.asarray(r.randn(N, K), dt)          # row-sharded

    def fused(xs, a, b):
        h = cm.ag_matmul(xs, a, ("mp",), 0)
        return cm.matmul_rs(h, b, ("mp",), 0)

    def unfused(xs, a, b):
        h = lax.all_gather(xs, ("mp",), axis=0, tiled=True) @ a
        return lax.psum_scatter(h @ b, "mp", scatter_dimension=0,
                                tiled=True)

    in_specs = (P("mp"), P(None, "mp"), P("mp"))

    def timed(fn):
        step = jax.jit(_shard_map(fn, mesh, in_specs, P("mp")))
        step(x, w1, w2).block_until_ready()      # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = step(x, w1, w2)
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters * 1e3

    fused_ms = timed(fused)
    unfused_ms = timed(unfused)
    _emit({
        "metric": "tp_overlap_matmul_ms",
        "value": round(fused_ms, 3),
        "unit": "ms",
        # the gate on chip: fused must not be slower than unfused
        "vs_baseline": round(unfused_ms / fused_ms, 4) if fused_ms else 0.0,
        "unfused_ms": round(unfused_ms, 3),
        "shape": [S, B, K, N], "dtype": str(jnp.dtype(dt)),
        "devices": n,
        "device": str(getattr(dev, "device_kind", dev.platform)),
    })


# ---------------------------------------------------------------------------
# On-chip Pallas kernel parity (CI runs the kernels in interpret mode on
# CPU only; this is the real-hardware numerics gate, flagged in VERDICT)
# ---------------------------------------------------------------------------
def bench_kernel_parity(on_tpu, dev):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.llama import _cache_attention_dense
    from paddle_tpu.ops.pallas.decode_attention import decode_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd

    interpret = not on_tpu
    r = np.random.RandomState(0)
    B, S, H, D = 2, 512, 4, 128
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    q = jnp.asarray(r.randn(B, S, H, D), dt)
    k = jnp.asarray(r.randn(B, S, H, D), dt)
    v = jnp.asarray(r.randn(B, S, H, D), dt)

    def xla_ref(q, k, v):
        qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
        kf = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
        vf = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
        s = jnp.einsum("bhsd,bhtd->bhst", qf, kf) / np.sqrt(D)
        keep = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(keep[None, None], s, -1e30)
        p = jax.nn.softmax(s, -1)
        return jnp.swapaxes(
            jnp.einsum("bhst,bhtd->bhsd", p, vf), 1, 2).astype(q.dtype)

    out = flash_attention_fwd(q, k, v, True, None, interpret)
    ref = xla_ref(q, k, v)
    fwd_err = float(jnp.abs(out.astype(jnp.float32)
                            - ref.astype(jnp.float32)).max())
    gk = jax.grad(lambda k: flash_attention_fwd(
        q, k, v, True, None, interpret).astype(jnp.float32).sum())(k)
    gr = jax.grad(lambda k: xla_ref(q, k, v).astype(
        jnp.float32).sum())(k)
    bwd_err = float(jnp.abs(gk.astype(jnp.float32)
                            - gr.astype(jnp.float32)).max())

    # decode kernel vs dense cache attention (serving shape)
    M, KV = 1024, 4
    qd = jnp.asarray(r.randn(1, 1, H, D), dt)
    kc = jnp.asarray(r.randn(1, KV, M, D), dt)
    vc = jnp.asarray(r.randn(1, KV, M, D), dt)
    dk = decode_attention(qd, kc, vc, 900, interpret=interpret)
    dd = _cache_attention_dense(qd, kc, vc, 900, 1)
    dec_err = float(jnp.abs(dk.astype(jnp.float32)
                            - dd.astype(jnp.float32)).max())

    # paged (block-table) kernel vs gathered dense, scrambled pages
    from paddle_tpu.ops.pallas.decode_attention import (
        paged_attention_dense, paged_decode_attention)

    page = 128
    npages = M // page
    P = npages + 3
    kp = jnp.asarray(r.randn(P, KV, page, D), dt)
    vp = jnp.asarray(r.randn(P, KV, page, D), dt)
    tbl = jnp.asarray(r.permutation(P)[:npages].reshape(1, npages),
                      jnp.int32)
    lens = jnp.asarray([900], jnp.int32)
    pk = paged_decode_attention(qd, kp, vp, tbl, lens,
                                interpret=interpret)
    pd = paged_attention_dense(qd, kp, vp, tbl, lens)
    paged_err = float(jnp.abs(pk.astype(jnp.float32)
                              - pd.astype(jnp.float32)).max())

    tol = 0.05 if on_tpu else 1e-4  # bf16 vs f32-ref on chip
    ok = (fwd_err < tol and bwd_err < 20 * tol and dec_err < tol
          and paged_err < tol)
    _emit({
        "metric": "pallas_kernel_parity_onchip" if on_tpu
        else "pallas_kernel_parity_interpret",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": 1.0 if ok else 0.0,
        "flash_fwd_max_err": round(fwd_err, 5),
        "flash_bwd_max_err": round(bwd_err, 5),
        "decode_max_err": round(dec_err, 5),
        "paged_max_err": round(paged_err, 5),
        "device": str(getattr(dev, "device_kind", dev.platform)),
    })


# ---------------------------------------------------------------------------
# 2. GPT-3 1.3B training MFU (BASELINE row 2) - the headline, printed last
# ---------------------------------------------------------------------------
def bench_gpt(on_tpu, dev):
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, \
        GPTPretrainingCriterion

    peak, _ = _chip(dev)
    if on_tpu:
        # GPT-3 1.3B (BASELINE config: Fleet TP - degree 1 on one chip):
        # hidden 2048 x 24 layers, d_head 128. bf16 params + bf16 moments
        # (AdamW math in f32) to fit the 16GB HBM of a v5e chip.
        # Larger batch = more MXU work per step; B=8 and B=6 were queued
        # in round 4 but never driver-verified (tunnel outage), so try
        # them HERE with an OOM fallback to the proven B=4.
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                        num_heads=16, max_position_embeddings=1024,
                        dtype="bfloat16")
        B_cands, S, steps = (8, 6, 4), 1024, 5
        state_dtype = "bfloat16"
    else:  # CPU smoke config so bench runs anywhere
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=128)
        B_cands, S, steps = (4,), 64, 2
        state_dtype = None

    r = np.random.RandomState(0)

    def attempt(B):
        # all state local: an OOM at any stage frees its buffers when
        # the frame exits, so the next batch size starts clean
        paddle.seed(0)
        model = GPTForCausalLM(cfg)  # dtype casts params on TPU
        crit = GPTPretrainingCriterion(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     state_dtype=state_dtype)
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        eng = ParallelEngine(model, opt, hcg.mesh)
        step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))
        ids = r.randint(0, cfg.vocab_size, (B, S + 1))
        batch = {"x": paddle.to_tensor(ids[:, :-1]),
                 "y": paddle.to_tensor(ids[:, 1:])}
        loss = step(batch)  # compile + warmup (OOM raises here)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(batch)
        float(loss)
        return B * S * steps / (time.perf_counter() - t0)

    best = None
    for B in B_cands:
        try:
            best = (B, attempt(B))
            break
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            if B == B_cands[-1]:
                raise
            _emit({"metric": "gpt_batch_probe", "value": float(B),
                   "unit": "skipped", "vs_baseline": 0.0,
                   "error": f"B={B}: {type(e).__name__}: {e}"[:300]})

    B, tok_s = best
    n_params = cfg.num_params()
    mfu = (6.0 * n_params * tok_s / peak) if peak else 0.0
    if on_tpu:
        _emit({
            "metric": "gpt1p3b_train_mfu",
            "value": round(mfu, 4),
            "unit": "mfu",
            "vs_baseline": round(mfu / 0.45, 4),
            "tokens_per_sec_per_chip": round(tok_s, 2),
            "batch": B,
            "device": str(getattr(dev, "device_kind", dev.platform)),
            "params": n_params,
            "telemetry": _telemetry_section(),
        })
    else:
        _emit({
            "metric": "gpt_smoke_train_tokens_per_sec",
            "value": round(tok_s, 2),
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "telemetry": _telemetry_section(),
        })


_BENCHES = {}

# Per-bench subprocess timeouts. gpt (the headline) gets the largest
# budget; everything else is short so a single hang can't eat the
# driver's budget (the round-4 blackout: kernel_parity first + 1200s
# each + headline printed last = one hang, zero lines).
_TIMEOUTS = {"gpt": 900, "llama_decode": 420, "llama_decode_int8": 420,
             "llama_decode_ragged": 420, "serving": 420,
             "serving_chunked": 600, "serving_prefix_spec": 600,
             "serving_disagg": 600,
             "resnet": 300,
             "moe": 300, "gpt_moe_hybrid": 420, "gpt13b_hybrid": 900,
             "tp_overlap": 240, "kernel_parity": 240,
             "ckpt_overlap": 420}
_ORDER = ("gpt", "llama_decode", "llama_decode_int8",
          "llama_decode_ragged", "serving", "serving_chunked",
          "serving_prefix_spec", "serving_disagg", "resnet",
          "moe", "gpt_moe_hybrid", "gpt13b_hybrid", "ckpt_overlap",
          "tp_overlap", "kernel_parity")
# benches that need a virtual multi-device mesh on the CPU fallback
_NEEDS_VDEV = {"gpt13b_hybrid": 8, "tp_overlap": 8, "gpt_moe_hybrid": 8,
               "ckpt_overlap": 8}


def _run_one(name, deadline_s=None):
    import os
    import traceback

    # The watchdog must be armed BEFORE any jax backend init: when the
    # axon tunnel is down, jax.devices() blocks forever in C code, and
    # only os._exit from another thread (or a parent kill) escapes.
    # Direct `--only` runs (bench_experiments.py) get the same bound.
    if deadline_s is None:  # explicit 0 disables the watchdog
        deadline_s = _TIMEOUTS.get(name, 600)
    if deadline_s > 0:
        import faulthandler
        import threading

        # Stack dump (to stderr; the parent re-prints stderr on
        # failure) fires BEFORE _die so the hang location is captured,
        # then _die emits the machine-readable line and exits.
        faulthandler.dump_traceback_later(max(deadline_s - 30, 3),
                                          exit=False)

        def _die():
            _emit({"metric": f"bench_{name}", "value": 0.0,
                   "unit": "error", "vs_baseline": 0.0,
                   "error": f"watchdog: exceeded {deadline_s - 15}s "
                            "(stack on stderr)"})
            os._exit(3)

        t = threading.Timer(max(deadline_s - 15, 5), _die)
        t.daemon = True
        t.start()

    if os.environ.get("BENCH_FORCE_CPU"):
        # The sitecustomize force-selects the hanging 'axon' platform via
        # jax.config, so the env var JAX_PLATFORMS alone is NOT enough
        # (tests/conftest.py has the same note) - update jax.config
        # before any backend initialises.
        os.environ["JAX_PLATFORMS"] = "cpu"
        nv = _NEEDS_VDEV.get(name)
        if nv:
            import re

            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count"
                        f"={nv}").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    dev = jax.devices()[0]
    on_tpu = _chip(dev)[0] > 0
    fn = _BENCHES[name]
    try:
        fn(on_tpu, dev)
    except Exception as e:
        _emit({"metric": fn.__name__, "value": 0.0, "unit": "error",
               "vs_baseline": 0.0,
               "error": f"{type(e).__name__}: {e}",
               "trace": traceback.format_exc()[-400:]})


def bench_llama_decode_int8(on_tpu, dev):
    bench_llama_decode(on_tpu, dev, weight_only=True)


_PROBE_SRC = """
import jax, jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
y = (x @ x).block_until_ready()
d = jax.devices()[0]
print("CHIP_OK", float(jnp.asarray(y, jnp.float32)[0, 0]),
      getattr(d, "device_kind", d.platform), flush=True)
"""


def _probe_chip():
    """Decide on_tpu WITHOUT touching jax in this process.

    Root cause of the round-4 bench blackout: when the axon TPU tunnel
    is down, PJRT client creation (make_c_api_client) blocks forever in
    C code - jax.devices() itself hangs, before any bench logic runs.
    Only a killable subprocess can probe safely. One 45s try, one 120s
    retry (first client creation can be slow), else fall back to CPU so
    every bench still emits its smoke line.
    """
    import subprocess

    for tmo in (45, 120):
        t0 = time.perf_counter()
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                               capture_output=True, text=True, timeout=tmo)
        except subprocess.TimeoutExpired:
            continue
        if "CHIP_OK" in (r.stdout or ""):
            kind = r.stdout.split("CHIP_OK", 1)[1].split()[1:]
            _emit({"metric": "chip_probe", "value": 1.0, "unit": "ok",
                   "vs_baseline": 1.0, "probe_s": round(
                       time.perf_counter() - t0, 1),
                   "device": " ".join(kind)})
            return True
    _emit({"metric": "chip_probe", "value": 0.0, "unit": "ok",
           "vs_baseline": 0.0,
           "error": "TPU client creation hung/failed twice; "
                    "benches fall back to CPU smoke configs"})
    return False


def main(argv):
    _BENCHES.update(resnet=bench_resnet, moe=bench_moe,
                    llama_decode=bench_llama_decode, gpt=bench_gpt,
                    kernel_parity=bench_kernel_parity,
                    llama_decode_int8=bench_llama_decode_int8,
                    llama_decode_ragged=bench_llama_decode_ragged,
                    serving=bench_serving_mixed,
                    serving_chunked=bench_serving_chunked,
                    serving_prefix_spec=bench_serving_prefix_spec,
                    serving_disagg=bench_serving_disagg,
                    gpt_moe_hybrid=bench_gpt_moe_hybrid,
                    gpt13b_hybrid=bench_gpt13b_hybrid,
                    ckpt_overlap=bench_ckpt_overlap,
                    tp_overlap=bench_tp_overlap)
    if len(argv) > 1 and argv[1] == "--only":
        dl = int(argv[3]) if len(argv) > 3 else None
        _run_one(argv[2], dl)
        return
    # Each bench runs in its OWN process: TPU HBM is only reliably
    # released at process exit (compiled executables pin buffers), and
    # the 7B decode + 1.3B train benches each need most of a v5e chip.
    # The parent NEVER imports jax (see _probe_chip).
    import os
    import subprocess

    on_tpu = _probe_chip()
    env = dict(os.environ)
    if not on_tpu:
        env["BENCH_FORCE_CPU"] = "1"

    headline_lines = []
    for name in _ORDER:
        tmo = _TIMEOUTS[name]
        out, err, synth = "", "", None
        try:
            r = subprocess.run(
                [sys.executable, __file__, "--only", name, str(tmo)],
                capture_output=True, text=True, timeout=tmo, env=env)
            out, err = r.stdout or "", r.stderr or ""
        except subprocess.TimeoutExpired as e:
            def _s(x):
                return (x.decode() if isinstance(x, bytes) else x) or ""
            out, err = _s(e.stdout), _s(e.stderr)
            synth = {"metric": f"bench_{name}", "value": 0.0,
                     "unit": "error", "vs_baseline": 0.0,
                     "error": f"timeout after {tmo}s (parent kill)"}
        except Exception as e:  # a hung bench must not drop later lines
            synth = {"metric": f"bench_{name}", "value": 0.0,
                     "unit": "error", "vs_baseline": 0.0,
                     "error": f"{type(e).__name__}: {e}"}
        if synth is not None:
            _emit(synth)
        if out:
            print(out, end="" if out.endswith("\n") else "\n", flush=True)
        if err.strip():  # watchdog stack dumps / crash tracebacks
            sys.stderr.write(err[-4000:])
            sys.stderr.flush()
        if name == "gpt":
            def _valid(ln):
                # a timed-out child can leave a truncated final line;
                # only well-formed JSON may become the headline
                try:
                    json.loads(ln)
                    return True
                except ValueError:
                    return False
            headline_lines = [ln for ln in out.splitlines()
                              if '"metric"' in ln and _valid(ln)]
            if not headline_lines and synth is not None:
                headline_lines = [json.dumps(synth)]
        # The headline runs FIRST (so a later hang can't kill it) but
        # single-line parsers take the LAST line - re-emit it after
        # EVERY bench (including right after gpt: its own stdout can
        # end in stray WARNING lines), so a driver-level kill at any
        # point leaves the headline as the last complete line.
        for ln in headline_lines:
            print(ln, flush=True)


if __name__ == "__main__":
    main(sys.argv)
