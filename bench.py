"""Benchmark: GPT-3 1.3B training on TPU (BASELINE.md config 2).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
metric/value = measured model FLOPs utilization (MFU = 6*N*tok_s/peak —
recompute FLOPs excluded, so remat lowers measured MFU honestly);
vs_baseline = MFU over the 45%-MFU north-star target (the reference
publishes no absolute numbers — BASELINE.md). Extra keys carry
tokens/sec/chip and the device generation for the record.

On CPU (no TPU attached) runs a tiny smoke config so the bench always
produces a line.
"""
import json
import time

import numpy as np

# Peak dense bf16 FLOPs per chip by TPU generation (public specs).
_PEAK = {
    "v4": 275e12, "v5e": 197e12, "v5 lite": 197e12, "v5litepod": 197e12,
    "v5p": 459e12, "v6e": 918e12, "v6 lite": 918e12,
}


def _peak_flops(device) -> float:
    kind = str(getattr(device, "device_kind", "")).lower()
    for k, v in _PEAK.items():
        if k in kind:
            return v
    if "tpu" in str(getattr(device, "platform", "")).lower():
        return 459e12  # unknown generation: assume v5p
    return 0.0  # CPU: MFU not meaningful


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, \
        GPTPretrainingCriterion

    dev = jax.devices()[0]
    peak = _peak_flops(dev)
    on_tpu = peak > 0

    if on_tpu:
        # GPT-3 1.3B (BASELINE config: Fleet TP — degree 1 on one chip):
        # hidden 2048 x 24 layers, d_head 128. bf16 params + bf16 moments
        # (AdamW math in f32) to fit the 16GB HBM of a v5e chip.
        cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                        num_heads=16, max_position_embeddings=1024,
                        dtype="bfloat16")
        B, S, steps = 4, 1024, 5
        state_dtype = "bfloat16"
    else:  # CPU smoke config so bench runs anywhere
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=128)
        B, S, steps = 4, 64, 2
        state_dtype = None

    paddle.seed(0)
    model = GPTForCausalLM(cfg)  # cfg.dtype='bfloat16' casts params on TPU
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 state_dtype=state_dtype)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))

    r = np.random.RandomState(0)
    ids = r.randint(0, cfg.vocab_size, (B, S + 1))
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}

    loss = step(batch)  # compile + warmup
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(batch)
    float(loss)
    dt = time.perf_counter() - t0

    tok_s = B * S * steps / dt
    n_params = cfg.num_params()
    mfu = (6.0 * n_params * tok_s / peak) if peak else 0.0
    if on_tpu:
        print(json.dumps({
            "metric": "gpt1p3b_train_mfu",
            "value": round(mfu, 4),
            "unit": "mfu",
            "vs_baseline": round(mfu / 0.45, 4),
            "tokens_per_sec_per_chip": round(tok_s, 2),
            "device": str(getattr(dev, "device_kind", dev.platform)),
            "params": n_params,
        }))
    else:
        print(json.dumps({
            "metric": "gpt_smoke_train_tokens_per_sec",
            "value": round(tok_s, 2),
            "unit": "tokens/s",
            "vs_baseline": 0.0,
        }))


if __name__ == "__main__":
    main()
