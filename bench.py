"""Benchmark: GPT-125M training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
MFU = 6 * params * tokens_per_sec / peak_flops; vs_baseline is measured
MFU over the north-star 45% target (BASELINE.md — the reference publishes
no absolute numbers, so the target is the baseline).
"""
import json
import os
import sys
import time

import numpy as np

# Peak dense bf16 FLOPs per chip by TPU generation (public specs).
_PEAK = {
    "v4": 275e12, "v5e": 197e12, "v5 lite": 197e12, "v5litepod": 197e12,
    "v5p": 459e12, "v6e": 918e12, "v6 lite": 918e12,
}


def _peak_flops(device) -> float:
    kind = str(getattr(device, "device_kind", "")).lower()
    for k, v in _PEAK.items():
        if k in kind:
            return v
    if "tpu" in str(getattr(device, "platform", "")).lower():
        return 459e12  # unknown generation: assume v5p
    return 0.0  # CPU: MFU not meaningful


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.engine import ParallelEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, \
        GPTPretrainingCriterion

    dev = jax.devices()[0]
    on_tpu = "tpu" in str(dev.platform).lower() or _peak_flops(dev) > 0

    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_position_embeddings=1024,
                        dtype="bfloat16")
        B, S, steps = 8, 1024, 5
    else:  # CPU smoke config so bench runs anywhere
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=128)
        B, S, steps = 4, 64, 2

    paddle.seed(0)
    model = GPTForCausalLM(cfg)  # cfg.dtype='bfloat16' casts params on TPU
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    eng = ParallelEngine(model, opt, hcg.mesh)
    step = eng.train_step(lambda m, b: crit(m(b["x"]), b["y"]))

    r = np.random.RandomState(0)
    ids = r.randint(0, cfg.vocab_size, (B, S + 1))
    batch = {"x": paddle.to_tensor(ids[:, :-1]),
             "y": paddle.to_tensor(ids[:, 1:])}

    loss = step(batch)  # compile + warmup
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(batch)
    float(loss)
    dt = time.perf_counter() - t0

    tok_s = B * S * steps / dt
    n_params = cfg.num_params()
    peak = _peak_flops(dev)
    mfu = (6.0 * n_params * tok_s / peak) if peak else 0.0
    print(json.dumps({
        "metric": "gpt125m_train_tokens_per_sec_per_chip" if on_tpu
        else "gpt_smoke_train_tokens_per_sec",
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
    }))


if __name__ == "__main__":
    main()
